"""Unit tests for the static-analysis package (``dmtpu check``).

Every rule id gets at least one firing fixture and one clean fixture,
plus engine behavior: inline suppressions, baseline matching (including
stale entries), the JSON report schema, and parse-error reporting.
All fixtures go through ``Project.from_sources`` — no disk, no jax.
"""

from __future__ import annotations

import json

import pytest

from distributedmandelbrot_tpu import analysis
from distributedmandelbrot_tpu.analysis import (Project, all_rules,
                                                check_project, run_check)

P = "distributedmandelbrot_tpu"


def findings_for(sources: dict[str, str], rule: str) -> list:
    project = Project.from_sources(sources)
    return [f for f in check_project(project) if f.rule == rule]


# -- catalogue -------------------------------------------------------------

def test_rule_catalogue_covers_all_families():
    rules = all_rules()
    families = {r.family for r in rules.values()}
    assert {"locks", "async", "wire", "jax", "engine",
            "proto", "res", "obs", "fsm"} <= families
    for rule in rules.values():
        assert rule.severity in ("error", "warning")
        assert rule.doc


def test_unknown_rule_id_rejected():
    with pytest.raises(ValueError, match="unknown rule ids"):
        check_project(Project.from_sources({}), ["no-such-rule"])


def test_rules_accept_family_names():
    ids = analysis.expand_rule_ids(["proto", "res", "obs-name"])
    assert {"proto-dispatch", "proto-frames", "proto-exact-read",
            "res-thread-join", "obs-name"} <= set(ids)
    # A family name selects its rules at check time too.
    assert check_project(Project.from_sources({}), ["proto"]) == []
    with pytest.raises(ValueError, match="families"):
        analysis.expand_rule_ids(["no-such-family"])


# -- locks -----------------------------------------------------------------

LOCK_GUARD_FIRE = f"{P}/serve/stateful.py"

LOCK_CLASS = '''
import threading

class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def put(self, k, v):
        with self._lock:
            self._items[k] = v

    def rogue(self, k):
        self._items.pop(k, None)
'''


def test_lock_guard_fires_on_unlocked_mutation():
    found = findings_for({LOCK_GUARD_FIRE: LOCK_CLASS}, "lock-guard")
    assert len(found) == 1
    f = found[0]
    assert f.severity == "error"
    assert "_items" in f.message and "rogue" in f.message


def test_lock_guard_clean_when_mutation_is_locked():
    src = LOCK_CLASS.replace(
        "        self._items.pop(k, None)",
        "        with self._lock:\n            self._items.pop(k, None)")
    assert findings_for({LOCK_GUARD_FIRE: src}, "lock-guard") == []


def test_lock_guard_ignores_init_and_out_of_scope_dirs():
    # __init__ writes without the lock by design; and the same rogue
    # class outside coordinator/storage/serve/obs is not scanned.
    assert findings_for({f"{P}/core/stateful.py": LOCK_CLASS},
                        "lock-guard") == []


LOCK_ORDER_CYCLE = f'''
class A:
    def f(self):
        with self._a:
            with self._b:
                pass

    def g(self):
        with self._b:
            with self._a:
                pass
'''


def test_lock_order_reports_cycle():
    found = findings_for({f"{P}/storage/locks.py": LOCK_ORDER_CYCLE},
                         "lock-order")
    assert len(found) == 1
    assert "A._a" in found[0].message and "A._b" in found[0].message


def test_lock_order_clean_on_consistent_order():
    src = LOCK_ORDER_CYCLE.replace(
        "        with self._b:\n            with self._a:",
        "        with self._a:\n            with self._b:")
    assert findings_for({f"{P}/storage/locks.py": src}, "lock-order") == []


def test_lock_order_sees_through_same_class_calls():
    src = '''
class A:
    def outer(self):
        with self._a:
            self.inner()

    def inner(self):
        with self._b:
            pass

    def inverted(self):
        with self._b:
            with self._a:
                pass
'''
    found = findings_for({f"{P}/obs/locks.py": src}, "lock-order")
    assert len(found) == 1


LOCK_BLOCKING_CLASS = '''
import queue
import threading

class Pipe:
    def __init__(self):
        self._cond = threading.Condition()
        self._q = queue.Queue()

    def bad(self):
        with self._cond:
            item = self._q.get()
        return item

    def good(self):
        with self._cond:
            self._cond.wait(timeout=0.1)
        return self._q.get()
'''


def test_lock_held_blocking_fires_on_queue_get_under_lock():
    found = findings_for({f"{P}/worker/pipe.py": LOCK_BLOCKING_CLASS},
                         "lock-held-blocking")
    assert len(found) == 1
    f = found[0]
    assert f.severity == "error"
    assert ".get()" in f.message and "Pipe._cond" in f.message


def test_lock_held_blocking_covers_join_sem_event_and_client():
    src = '''
class W:
    def a(self):
        with self._lock:
            self.client.request_batch(4)

    def b(self):
        with self._lock:
            self._upload_thread.join()

    def c(self):
        with self._lock:
            self._dev_sem.acquire()

    def d(self):
        with self._lock:
            self._stop.wait(1.0)
'''
    found = findings_for({f"{P}/worker/w.py": src}, "lock-held-blocking")
    assert len(found) == 4


def test_lock_held_blocking_clean_cases():
    # Outside any lock; cond.wait on the HELD lock (the sanctioned
    # Condition protocol); dict .get under a lock; and the whole class
    # out of the scoped dirs.
    src = '''
class W:
    def a(self):
        item = self._q.get()
        with self._lock:
            self._seen = self._index.get(item)
        self._cond_other = 1

    def b(self):
        with self._cond:
            self._cond.wait(timeout=0.5)
            self._cond.notify_all()
'''
    assert findings_for({f"{P}/worker/w.py": src},
                        "lock-held-blocking") == []
    assert findings_for({f"{P}/core/pipe.py": LOCK_BLOCKING_CLASS},
                        "lock-held-blocking") == []


# -- locks: interprocedural (v2) -------------------------------------------

WRAPPED_BLOCKING = '''
import queue
import threading

class Stage:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()

    def _drain_one(self):
        return self._q.get()

    def bad(self):
        with self._lock:
            item = self._drain_one()
        return item
'''


def test_lock_held_blocking_sees_through_helper():
    # A one-level wrapper must not defeat the rule: bad() holds _lock
    # while calling _drain_one(), whose body blocks on the queue.
    found = findings_for({f"{P}/worker/stage.py": WRAPPED_BLOCKING},
                         "lock-held-blocking")
    assert len(found) == 1
    f = found[0]
    assert "reached via" in f.message and "_drain_one" in f.message
    assert "Stage._lock" in f.message


def test_lock_held_blocking_clean_when_helper_blocks_outside_lock():
    src = WRAPPED_BLOCKING.replace(
        "        with self._lock:\n            item = self._drain_one()",
        "        item = self._drain_one()\n        with self._lock:\n"
        "            self._seen = item")
    assert findings_for({f"{P}/worker/stage.py": src},
                        "lock-held-blocking") == []


CROSS_CLASS_CYCLE = '''
import threading

class A:
    def __init__(self, b: "B"):
        self.b = b
        self._la = threading.Lock()

    def f(self):
        with self._la:
            self.b.g()

    def grab(self):
        with self._la:
            pass

class B:
    def __init__(self, a: "A"):
        self.a = a
        self._lb = threading.Lock()

    def g(self):
        with self._lb:
            pass

    def h(self):
        with self._lb:
            self.a.grab()
'''


def test_lock_order_cycle_across_classes_via_call_graph():
    # A.f: holds A._la, calls B.g which takes B._lb; B.h holds B._lb and
    # calls A.grab which takes A._la.  Neither file nests two ``with``
    # blocks lexically — only the call graph sees the cycle.
    found = findings_for({f"{P}/storage/ab.py": CROSS_CLASS_CYCLE},
                         "lock-order")
    assert len(found) == 1
    assert "A._la" in found[0].message and "B._lb" in found[0].message


def test_lock_order_clean_when_cross_class_order_is_consistent():
    src = CROSS_CLASS_CYCLE.replace(
        "        with self._lb:\n            self.a.grab()",
        "        self.a.grab()\n        with self._lb:\n            pass")
    assert findings_for({f"{P}/storage/ab.py": src}, "lock-order") == []


# -- async -----------------------------------------------------------------

def test_async_blocking_fires_on_time_sleep_and_sync_framing():
    src = '''
import time
from distributedmandelbrot_tpu.net import framing

class Handler:
    async def handle(self, sock):
        time.sleep(0.1)
        framing.send_u32(sock, 1)
'''
    found = findings_for({f"{P}/serve/h.py": src}, "async-blocking")
    assert len(found) == 2
    assert any("time.sleep" in f.message for f in found)
    assert any("send_u32" in f.message for f in found)


def test_async_blocking_clean_via_to_thread_and_async_framing():
    src = '''
import asyncio
from distributedmandelbrot_tpu.net import framing

class Handler:
    async def handle(self, reader):
        await asyncio.sleep(0.1)
        n = await framing.read_u32(reader)
        payload = await asyncio.to_thread(self.store.load_payload, n, 0, 0)
        return payload
'''
    assert findings_for({f"{P}/serve/h.py": src}, "async-blocking") == []


def test_async_blocking_only_inside_async_defs():
    src = '''
import time

def sync_helper():
    time.sleep(0.1)
'''
    assert findings_for({f"{P}/serve/h.py": src}, "async-blocking") == []


def test_async_blocking_fires_on_sync_queue_in_coroutine():
    # The worker pipeline's stage queues are sync queue.Queue; feeding
    # one from a coroutine would park the whole event loop.  The
    # asyncio flavor is awaited (exempt), _nowait never blocks, and a
    # dict .get on a non-queue-named receiver is not a queue.
    src = '''
class G:
    async def pump(self):
        item = self._work_q.get()
        await self.handle(item)

    async def ok(self):
        item = await self._aio_queue.get()
        fast = self._work_q.get_nowait()
        meta = self.conf.get("k")
        return item, fast, meta

    async def handle(self, item):
        pass
'''
    found = findings_for({f"{P}/serve/pump.py": src}, "async-blocking")
    assert len(found) == 1
    assert "queue" in found[0].message and "await" in found[0].message


def test_async_unawaited_fires_on_bare_coroutine_call():
    src = '''
class G:
    async def go(self):
        pass

    async def run(self):
        self.go()
'''
    found = findings_for({f"{P}/serve/g.py": src}, "async-unawaited")
    assert len(found) == 1
    assert "self.go" in found[0].message


def test_async_unawaited_clean_when_awaited_or_scheduled():
    src = '''
import asyncio

class G:
    async def go(self):
        pass

    async def run(self):
        await self.go()
        task = asyncio.create_task(self.go())
        self._tasks.add(task)
'''
    assert findings_for({f"{P}/serve/g.py": src}, "async-unawaited") == []


def test_async_dropped_task_fires_and_kept_task_is_clean():
    fire = '''
import asyncio

async def work():
    pass

async def main():
    asyncio.create_task(work())
'''
    kept = '''
import asyncio

async def work():
    pass

async def main():
    task = asyncio.create_task(work())
    tasks.add(task)
    task.add_done_callback(tasks.discard)
'''
    assert len(findings_for({f"{P}/serve/t.py": fire},
                            "async-dropped-task")) == 1
    assert findings_for({f"{P}/serve/t.py": kept},
                        "async-dropped-task") == []


# -- wire ------------------------------------------------------------------

def test_wire_literal_fires_outside_canonical_modules():
    src = 'import struct\nHEADER = struct.Struct("<II")\n'
    found = findings_for({f"{P}/serve/proto_copy.py": src}, "wire-literal")
    assert len(found) == 1
    assert '"<II"' in found[0].message


def test_wire_literal_clean_in_canonical_modules():
    src = 'import struct\n_FMT = struct.Struct("<II")\n'
    for canonical in (f"{P}/net/protocol.py", f"{P}/codecs/custom.py"):
        assert findings_for({canonical: src}, "wire-literal") == []


def test_wire_size_fires_on_mismatched_constant():
    src = ('import struct\n'
           'QUERY = struct.Struct("<III")\n'
           'QUERY_WIRE_SIZE = 16\n')
    found = findings_for({f"{P}/net/protocol.py": src}, "wire-size")
    assert len(found) == 1
    assert "16" in found[0].message and "12" in found[0].message


def test_wire_size_fires_on_broken_query_tail_composition():
    src = ('import struct\n'
           'QUERY = struct.Struct("<III")\n'
           'QUERY_WIRE_SIZE = 12\n'
           'QUERY_TAIL = struct.Struct("<IQ")\n')
    found = findings_for({f"{P}/net/protocol.py": src}, "wire-size")
    assert len(found) == 1
    assert "byte-for-byte" in found[0].message


def test_wire_size_clean_on_consistent_constants():
    src = ('import struct\n'
           'QUERY = struct.Struct("<III")\n'
           'QUERY_WIRE_SIZE = 12\n'
           'QUERY_TAIL = struct.Struct("<II")\n'
           'QUERY_TAIL_WIRE_SIZE = 8\n')
    assert findings_for({f"{P}/net/protocol.py": src}, "wire-size") == []


def test_wire_parity_fires_when_speaker_retypes_format():
    src = ('import struct\n'
           '_QUERY = struct.Struct("<III")\n')
    found = findings_for({f"{P}/coordinator/dataserver.py": src},
                         "wire-parity")
    assert len(found) == 1
    assert "QUERY" in found[0].message


def test_wire_parity_clean_when_canonical_struct_used():
    src = ('from distributedmandelbrot_tpu.net import protocol as proto\n'
           'SIZE = proto.QUERY.size\n')
    assert findings_for({f"{P}/coordinator/dataserver.py": src},
                        "wire-parity") == []
    # Modules absent from the project are skipped, not reported.
    assert findings_for({f"{P}/serve/other.py": "x = 1\n"},
                        "wire-parity") == []


# -- jax -------------------------------------------------------------------

JIT_HEADER = ('from functools import partial\n'
              'import jax\n'
              'import jax.numpy as jnp\n'
              'import numpy as np\n')


def test_jax_impure_fires_on_print_time_random():
    src = JIT_HEADER + '''
import time, random

@partial(jax.jit, static_argnames=("n",))
def f(x, n):
    print(x)
    time.time()
    random.random()
    return x
'''
    found = findings_for({f"{P}/ops/kern.py": src}, "jax-impure")
    assert len(found) == 3


def test_jax_impure_clean_in_pure_jit_and_host_code():
    src = JIT_HEADER + '''
@partial(jax.jit, static_argnames=("n",))
def f(x, n):
    return jnp.sin(x) * n

def host_wrapper(x):
    print("host side is allowed to print")
    return f(x, 2)
'''
    assert findings_for({f"{P}/ops/kern.py": src}, "jax-impure") == []


def test_jax_impure_fires_inside_pallas_kernel():
    src = JIT_HEADER + '''
def kernel(x_ref, o_ref):
    print("trace me once")
    o_ref[...] = x_ref[...]

def run(pl, x):
    return pl.pallas_call(kernel, out_shape=x)(x)
'''
    found = findings_for({f"{P}/ops/pk.py": src}, "jax-impure")
    assert len(found) == 1


def test_jax_host_sync_fires_on_asarray_and_float():
    src = JIT_HEADER + '''
@jax.jit
def f(x):
    y = np.asarray(x)
    return float(x) + y.sum()
'''
    found = findings_for({f"{P}/parallel/sync.py": src}, "jax-host-sync")
    assert len(found) == 2


def test_jax_host_sync_clean_outside_traced_functions():
    src = JIT_HEADER + '''
def host(x):
    return float(np.asarray(x).sum())
'''
    assert findings_for({f"{P}/parallel/sync.py": src}, "jax-host-sync") == []


def test_jax_dtype_fires_without_precision_import():
    src = JIT_HEADER + '''
@jax.jit
def f(x):
    return x.astype("float64") + jnp.zeros((), np.int64)
'''
    found = findings_for({f"{P}/ops/dt.py": src}, "jax-dtype")
    assert len(found) == 2
    assert all(f.severity == "warning" for f in found)


def test_jax_dtype_clean_when_module_routes_through_precision():
    src = (JIT_HEADER
           + 'from distributedmandelbrot_tpu.utils.precision import '
             'ensure_x64\n'
           + '''
@jax.jit
def f(x):
    return x.astype("float64")
''')
    assert findings_for({f"{P}/ops/dt.py": src}, "jax-dtype") == []


def test_jax_dtype_mix_fires_on_half_literals_without_optin():
    src = JIT_HEADER + '''
@jax.jit
def f(x):
    y = x.astype("bfloat16") + jnp.zeros((), jnp.float16)
    return y.astype("half")
'''
    found = findings_for({f"{P}/ops/mix.py": src}, "jax-dtype-mix")
    assert len(found) == 3
    assert all(f.severity == "warning" for f in found)


def test_jax_dtype_mix_clean_with_mixed_precision_import():
    src = (JIT_HEADER
           + 'from distributedmandelbrot_tpu.ops.mixed_precision import '
             'scout_cast\n'
           + '''
@jax.jit
def f(x):
    return scout_cast(x) + x.astype("bfloat16")
''')
    assert findings_for({f"{P}/ops/mix.py": src}, "jax-dtype-mix") == []


def test_jax_dtype_mix_clean_outside_traced_functions():
    src = JIT_HEADER + '''
def host(x):
    return x.astype("bfloat16")
'''
    assert findings_for({f"{P}/ops/mix.py": src}, "jax-dtype-mix") == []


def test_jax_dtype_mix_fires_on_mxu_census_without_gateway():
    """An MXU-census-shaped module (jitted panel shadow downcasting to
    bf16 around a dot_general) that does NOT route through the
    mixed_precision gateway must fire per half-precision literal — the
    exact drift the sanctioned ops/mxu_iteration.py module avoids."""
    src = JIT_HEADER + '''
from jax import lax

@jax.jit
def census_panel(params):
    c = params.astype("bfloat16")
    z = jnp.zeros_like(c)
    state = jnp.stack([z, z], axis=-1)
    sq = lax.dot_general(state, state,
                         dimension_numbers=((( 1,), (1,)), ((), ())))
    return (sq.astype(jnp.bfloat16) + c).sum()
'''
    found = findings_for({f"{P}/ops/mxu_census.py": src},
                         "jax-dtype-mix")
    assert len(found) == 2
    assert all("bfloat16" in f.message or "half" in f.message
               for f in found)


def test_jax_dtype_mix_clean_on_mxu_census_via_gateway():
    """The same census shape routed through the mixed_precision gateway
    (the real ops/mxu_iteration.py pattern: scout_cast/scout_const as
    the only way values cross the precision boundary) stays clean."""
    src = (JIT_HEADER
           + 'from distributedmandelbrot_tpu.ops.mixed_precision import '
             'scout_cast, scout_const\n'
           + '''
from jax import lax

@jax.jit
def census_panel(params):
    c = scout_cast(params)
    four = scout_const(4.0)
    state = jnp.stack([c, c], axis=-1)
    sq = lax.dot_general(state, state,
                         dimension_numbers=(((1,), (1,)), ((), ())))
    return ((sq + c) >= four).sum()
''')
    assert findings_for({f"{P}/ops/mxu_census.py": src},
                        "jax-dtype-mix") == []


# -- proto -----------------------------------------------------------------

PROTO_MOD = f"{P}/net/protocol.py"
PROTO_SRC = '''
import struct

PURPOSE_REQUEST = 0x00

QUERY = struct.Struct("<III")
QUERY_WIRE_SIZE = QUERY.size
QUERY_TAIL = struct.Struct("<II")
QUERY_TAIL_WIRE_SIZE = QUERY_TAIL.size
'''

PROTO_CLIENT = f"{P}/worker/client.py"
CLIENT_SRC = '''
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.net.framing import (recv_u32, send_all,
                                                   send_byte)

class Client:
    def request(self, sock, a, b, c):
        send_byte(sock, proto.PURPOSE_REQUEST)
        send_all(sock, proto.QUERY.pack(a, b, c))
        return recv_u32(sock)
'''

PROTO_SERVER = f"{P}/coordinator/distributer.py"
SERVER_SRC = '''
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.net.framing import (recv_byte, recv_exact,
                                                   send_u32)

class Server:
    def handle(self, sock):
        purpose = recv_byte(sock)
        if purpose == proto.PURPOSE_REQUEST:
            raw = recv_exact(sock, proto.QUERY.size)
            a, b, c = proto.QUERY.unpack(raw)
            send_u32(sock, a)
'''

PROTO_SOURCES = {PROTO_MOD: PROTO_SRC, PROTO_CLIENT: CLIENT_SRC,
                 PROTO_SERVER: SERVER_SRC}


def test_proto_clean_on_matched_exchange():
    for rule in ("proto-dispatch", "proto-frames", "proto-exact-read"):
        assert findings_for(PROTO_SOURCES, rule) == []


def test_proto_dispatch_fires_on_purpose_with_no_arm():
    # The deliberately introduced dispatch gap: the server stops testing
    # the purpose byte, so PURPOSE_REQUEST has no arm.
    gap = dict(PROTO_SOURCES)
    gap[PROTO_SERVER] = SERVER_SRC.replace(
        "        if purpose == proto.PURPOSE_REQUEST:\n", "        if True:\n")
    found = findings_for(gap, "proto-dispatch")
    assert len(found) == 1
    assert "PURPOSE_REQUEST has no server dispatch arm" in found[0].message
    assert found[0].path == PROTO_MOD


def test_proto_dispatch_fires_on_purpose_with_no_emitter():
    gap = dict(PROTO_SOURCES)
    gap[PROTO_CLIENT] = CLIENT_SRC.replace(
        "        send_byte(sock, proto.PURPOSE_REQUEST)\n", "")
    found = findings_for(gap, "proto-dispatch")
    assert len(found) == 1
    assert "no client emitter" in found[0].message


def test_proto_frames_fires_on_struct_disagreement():
    skewed = dict(PROTO_SOURCES)
    skewed[PROTO_SERVER] = SERVER_SRC.replace(
        "recv_exact(sock, proto.QUERY.size)",
        "recv_exact(sock, proto.QUERY_TAIL.size)").replace(
        "proto.QUERY.unpack(raw)", "proto.QUERY_TAIL.unpack(raw)")
    found = findings_for(skewed, "proto-frames")
    assert len(found) == 1
    assert "client sends [QUERY]" in found[0].message
    assert "server reads [QUERY_TAIL]" in found[0].message


def test_proto_frames_sees_through_helper_and_collapses_loops():
    # The emitter delegates the frame writes to a helper and the server
    # reads the struct in a loop — both must still compare clean.
    spliced = dict(PROTO_SOURCES)
    spliced[PROTO_CLIENT] = '''
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.net.framing import (recv_u32, send_all,
                                                   send_byte)

class Client:
    def _emit_query(self, sock, a, b, c):
        send_all(sock, proto.QUERY.pack(a, b, c))

    def request(self, sock, a, b, c):
        send_byte(sock, proto.PURPOSE_REQUEST)
        self._emit_query(sock, a, b, c)
        return recv_u32(sock)
'''
    spliced[PROTO_SERVER] = SERVER_SRC.replace(
        "            raw = recv_exact(sock, proto.QUERY.size)\n",
        "            for _ in range(3):\n"
        "                raw = recv_exact(sock, proto.QUERY.size)\n")
    assert findings_for(spliced, "proto-frames") == []


def test_proto_exact_read_fires_on_raw_recv():
    raw = dict(PROTO_SOURCES)
    raw[PROTO_SERVER] = SERVER_SRC.replace(
        "recv_exact(sock, proto.QUERY.size)", "sock.recv(12)")
    found = findings_for(raw, "proto-exact-read")
    assert len(found) == 1
    assert "raw .recv()" in found[0].message


def test_proto_exact_read_fires_on_wrong_struct_size():
    wrong = dict(PROTO_SOURCES)
    wrong[PROTO_SERVER] = SERVER_SRC.replace(
        "recv_exact(sock, proto.QUERY.size)",
        "recv_exact(sock, proto.QUERY_TAIL.size)")
    found = findings_for(wrong, "proto-exact-read")
    assert len(found) == 1
    assert "sized as QUERY_TAIL, not QUERY" in found[0].message


def test_proto_silent_without_protocol_module():
    # Fixture projects with no net/protocol.py are out of scope.
    assert findings_for({PROTO_CLIENT: CLIENT_SRC}, "proto-dispatch") == []


# A stream-upgrade purpose (STREAM_FRAME_SYMBOLS): after the hello the
# connection multiplexes SESSION_FRAME-headed frames, so sequence parity
# covers only the ops before the first SESSION_FRAME on each side.
SESSION_PROTO_SRC = '''
import struct

PURPOSE_SESSION = 0x05

SESSION_HELLO = struct.Struct("<I")
SESSION_HELLO_WIRE_SIZE = SESSION_HELLO.size
SESSION_FRAME = struct.Struct("<BHI")
SESSION_FRAME_WIRE_SIZE = SESSION_FRAME.size
'''

SESSION_CLIENT_SRC = '''
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.net.framing import (recv_byte, recv_exact,
                                                   send_all, send_byte)

class Session:
    def connect(self, sock, want):
        send_byte(sock, proto.PURPOSE_SESSION)
        send_all(sock, proto.SESSION_HELLO.pack(want))
        status = recv_byte(sock)
        raw = recv_exact(sock, proto.SESSION_HELLO_WIRE_SIZE)
        return status, proto.SESSION_HELLO.unpack(raw)[0]
'''

SESSION_SERVER_SRC = '''
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.net.framing import (recv_byte, recv_exact,
                                                   send_all, send_byte)

class Server:
    def handle(self, sock):
        purpose = recv_byte(sock)
        if purpose == proto.PURPOSE_SESSION:
            raw = recv_exact(sock, proto.SESSION_HELLO.size)
            (want,) = proto.SESSION_HELLO.unpack(raw)
            send_byte(sock, 0x50)
            send_all(sock, proto.SESSION_HELLO.pack(want))
            while True:
                hdr = recv_exact(sock, proto.SESSION_FRAME_WIRE_SIZE)
                kind, seq, length = proto.SESSION_FRAME.unpack(hdr)
                body = recv_exact(sock, length)
                send_all(sock, proto.SESSION_FRAME.pack(kind, seq, 0))
'''

SESSION_SOURCES = {PROTO_MOD: SESSION_PROTO_SRC,
                   PROTO_CLIENT: SESSION_CLIENT_SRC,
                   PROTO_SERVER: SESSION_SERVER_SRC}


def test_proto_session_parity_checks_hello_prefix_only():
    # The server arm's frame loop (recv SESSION_FRAME, recv ?, send
    # SESSION_FRAME) never mirrors the one-shot hello emitter; the
    # stream truncation keeps parity scoped to the hello handshake.
    assert findings_for(SESSION_SOURCES, "proto-frames") == []
    assert findings_for(SESSION_SOURCES, "proto-dispatch") == []


def test_proto_session_fires_on_hello_prefix_mismatch():
    # A drift *inside* the hello prefix still fires: the server stops
    # writing the accept byte before its hello echo.
    skewed = dict(SESSION_SOURCES)
    skewed[PROTO_SERVER] = SESSION_SERVER_SRC.replace(
        "            send_byte(sock, 0x50)\n", "")
    found = findings_for(skewed, "proto-frames")
    assert len(found) == 1
    assert "client awaits [BYTE, SESSION_HELLO]" in found[0].message
    assert "server writes [SESSION_HELLO]" in found[0].message


def test_proto_session_dispatch_fires_without_emitter():
    gap = dict(SESSION_SOURCES)
    gap[PROTO_CLIENT] = SESSION_CLIENT_SRC.replace(
        "        send_byte(sock, proto.PURPOSE_SESSION)\n", "")
    found = findings_for(gap, "proto-dispatch")
    assert len(found) == 1
    assert "PURPOSE_SESSION has no client emitter" in found[0].message


# The magic-dispatched rendered-tile exchange (QUERY_EXCHANGES entry
# "render_query"): no purpose byte — the gateway sniffs a magic u32 —
# so client emitter and server handler are paired by qualname.
RENDER_PROTO_SRC = PROTO_SRC + '''
RENDER_QUERY_TAIL = struct.Struct("<IIIBB")
RENDER_QUERY_TAIL_WIRE_SIZE = RENDER_QUERY_TAIL.size
'''

RENDER_CLIENT = f"{P}/viewer/client.py"
RENDER_CLIENT_SRC = '''
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.net.framing import (recv_byte, recv_exact,
                                                   recv_u32, send_all)

class DataClient:
    def _render_exchange(self, sock, level, i, j, colormap_id):
        send_all(sock, proto.RENDER_QUERY_TAIL.pack(level, i, j,
                                                    colormap_id, 0))
        status = recv_byte(sock)
        length = recv_u32(sock)
        return recv_exact(sock, length), status
'''

RENDER_SERVER = f"{P}/serve/gateway.py"
RENDER_SERVER_SRC = '''
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.net.framing import (read_exact, write_byte,
                                                   write_u32)

class TileGateway:
    async def _serve_render(self, reader, writer):
        raw = await read_exact(reader, proto.RENDER_QUERY_TAIL.size)
        level, i, j, colormap_id, flags = proto.RENDER_QUERY_TAIL.unpack(raw)
        body = self._render(level, i, j, colormap_id)
        write_byte(writer, 0x10)
        write_u32(writer, len(body))
        writer.write(body)
'''

RENDER_SOURCES = {PROTO_MOD: RENDER_PROTO_SRC,
                  RENDER_CLIENT: RENDER_CLIENT_SRC,
                  RENDER_SERVER: RENDER_SERVER_SRC}


def test_proto_render_exchange_clean_when_sequences_match():
    for rule in ("proto-frames", "proto-exact-read"):
        assert findings_for(RENDER_SOURCES, rule) == []


def test_proto_render_exchange_fires_when_client_sends_wrong_struct():
    # Version-skew drift: a client still speaking the raw-tile QUERY at
    # a render endpoint must be caught as a sequence mismatch.
    skewed = dict(RENDER_SOURCES)
    skewed[RENDER_CLIENT] = RENDER_CLIENT_SRC.replace(
        "proto.RENDER_QUERY_TAIL.pack(level, i, j,\n"
        "                                                    colormap_id, 0)",
        "proto.QUERY.pack(level, i, j)")
    found = findings_for(skewed, "proto-frames")
    assert len(found) == 1
    assert "render_query" in found[0].message
    assert "client sends [QUERY]" in found[0].message
    assert "server reads [RENDER_QUERY_TAIL]" in found[0].message
    assert found[0].path == RENDER_SERVER


def test_proto_render_exchange_fires_when_server_drops_status_byte():
    skewed = dict(RENDER_SOURCES)
    skewed[RENDER_SERVER] = RENDER_SERVER_SRC.replace(
        "        write_byte(writer, 0x10)\n", "")
    found = findings_for(skewed, "proto-frames")
    assert len(found) == 1
    assert "client awaits [BYTE, U32, ?]" in found[0].message
    assert "server writes [U32, ?]" in found[0].message


def test_proto_render_exchange_skipped_when_one_side_absent():
    # Exchange parity only applies when both qualnames exist — fixture
    # projects (and partial source sets) must stay silent.
    one_sided = {PROTO_MOD: RENDER_PROTO_SRC,
                 RENDER_CLIENT: RENDER_CLIENT_SRC}
    assert findings_for(one_sided, "proto-frames") == []


# The session-scoped query (QUERY_EXCHANGES entry "session_query"):
# magic sniffed like the render exchange, but the reply leads with a
# fixed SESSION_REPLY header (new session id + granted caps) before the
# standard status byte — the parity check must see that header on both
# sides.
SQUERY_PROTO_SRC = PROTO_SRC + '''
SESSION_QUERY_TAIL = struct.Struct("<QIIIBB")
SESSION_QUERY_TAIL_WIRE_SIZE = SESSION_QUERY_TAIL.size
SESSION_REPLY = struct.Struct("<QB")
SESSION_REPLY_WIRE_SIZE = SESSION_REPLY.size
'''

SQUERY_CLIENT = f"{P}/viewer/client.py"
SQUERY_CLIENT_SRC = '''
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.net.framing import (recv_byte, recv_exact,
                                                   recv_u32, send_all)

class DataClient:
    def _session_exchange(self, sock, session_id, level, i, j,
                          colormap_id, flags):
        send_all(sock, proto.SESSION_QUERY_TAIL.pack(
            session_id, level, i, j, colormap_id, flags))
        sid, caps = proto.SESSION_REPLY.unpack(
            recv_exact(sock, proto.SESSION_REPLY_WIRE_SIZE))
        status = recv_byte(sock)
        length = recv_u32(sock)
        return recv_exact(sock, length), status
'''

SQUERY_SERVER = f"{P}/serve/gateway.py"
SQUERY_SERVER_SRC = '''
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.net.framing import (read_exact, write_byte,
                                                   write_u32)

class TileGateway:
    async def _serve_session(self, reader, writer):
        raw = await read_exact(reader, proto.SESSION_QUERY_TAIL.size)
        (session_id, level, i, j,
         colormap_id, flags) = proto.SESSION_QUERY_TAIL.unpack(raw)
        sid, caps, body = self._resolve(session_id, level, i, j,
                                        colormap_id, flags)
        writer.write(proto.SESSION_REPLY.pack(sid, caps))
        write_byte(writer, 0x10)
        write_u32(writer, len(body))
        writer.write(body)
'''

SQUERY_SOURCES = {PROTO_MOD: SQUERY_PROTO_SRC,
                  SQUERY_CLIENT: SQUERY_CLIENT_SRC,
                  SQUERY_SERVER: SQUERY_SERVER_SRC}


def test_proto_session_query_clean_when_sequences_match():
    for rule in ("proto-frames", "proto-exact-read"):
        assert findings_for(SQUERY_SOURCES, rule) == []


def test_proto_session_query_fires_when_client_sends_legacy_tail():
    # Version-skew drift: a legacy client speaking the raw 12-byte QUERY
    # at the session magic must be caught as a sequence mismatch.
    skewed = dict(SQUERY_SOURCES)
    skewed[SQUERY_CLIENT] = SQUERY_CLIENT_SRC.replace(
        "proto.SESSION_QUERY_TAIL.pack(\n"
        "            session_id, level, i, j, colormap_id, flags)",
        "proto.QUERY.pack(level, i, j)")
    found = findings_for(skewed, "proto-frames")
    assert len(found) == 1
    assert "session_query" in found[0].message
    assert "client sends [QUERY]" in found[0].message
    assert "server reads [SESSION_QUERY_TAIL]" in found[0].message


def test_proto_session_query_fires_when_server_drops_reply_header():
    # The SESSION_REPLY header precedes the status byte; a server that
    # jumps straight to the status desynchronizes every client read.
    skewed = dict(SQUERY_SOURCES)
    skewed[SQUERY_SERVER] = SQUERY_SERVER_SRC.replace(
        "        writer.write(proto.SESSION_REPLY.pack(sid, caps))\n", "")
    found = findings_for(skewed, "proto-frames")
    assert len(found) == 1
    assert "client awaits [SESSION_REPLY, BYTE, U32, ?]" in found[0].message
    assert "server writes [BYTE, U32, ?]" in found[0].message


def test_proto_session_query_skipped_when_one_side_absent():
    one_sided = {PROTO_MOD: SQUERY_PROTO_SRC,
                 SQUERY_SERVER: SQUERY_SERVER_SRC}
    assert findings_for(one_sided, "proto-frames") == []


# The batched lease exchange (SESSION_EXCHANGES entry "lease_reqn"):
# an exchange INSIDE the multiplexed session stream, so ops carrying
# the frame-header symbol are filtered from both sides and the payload
# sequences (REQN out, GRANTN + grant groups back) must mirror.
GRANTN_PROTO_SRC = '''
import struct

SESSION_FRAME = struct.Struct("<BHI")
SESSION_FRAME_WIRE_SIZE = SESSION_FRAME.size
LEASE_REQN = struct.Struct("<II")
LEASE_REQN_WIRE_SIZE = LEASE_REQN.size
LEASE_GRANTN = struct.Struct("<II")
LEASE_GRANTN_WIRE_SIZE = LEASE_GRANTN.size
GRANT_WANT = struct.Struct("<I")
GRANT_WANT_WIRE_SIZE = GRANT_WANT.size
'''

GRANTN_CLIENT_SRC = '''
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.net.framing import (recv_exact, recv_u32,
                                                   send_all)

class DistributerSession:
    def _request_batchn(self, sock, max_count, width):
        send_all(sock, proto.SESSION_FRAME.pack(0x06, 0,
                                                proto.LEASE_REQN_WIRE_SIZE))
        send_all(sock, proto.LEASE_REQN.pack(max_count, width))
        hdr = recv_exact(sock, proto.SESSION_FRAME_WIRE_SIZE)
        raw = recv_exact(sock, proto.LEASE_GRANTN_WIRE_SIZE)
        n_batches, n_tiles = proto.LEASE_GRANTN.unpack(raw)
        for _ in range(n_batches):
            n = recv_u32(sock)
        return n_tiles
'''

GRANTN_SERVER_SRC = '''
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.net.framing import read_exact, write_u32

class Distributer:
    async def _session_lease_reqn(self, reader, writer, seq):
        raw = await read_exact(reader, proto.LEASE_REQN_WIRE_SIZE)
        count, width = proto.LEASE_REQN.unpack(raw)
        writer.write(proto.SESSION_FRAME.pack(0x07, seq,
                                              proto.LEASE_GRANTN_WIRE_SIZE))
        writer.write(proto.LEASE_GRANTN.pack(1, count))
        write_u32(writer, count)
'''

GRANTN_SOURCES = {PROTO_MOD: GRANTN_PROTO_SRC,
                  PROTO_CLIENT: GRANTN_CLIENT_SRC,
                  PROTO_SERVER: GRANTN_SERVER_SRC}


def test_proto_grantn_exchange_clean_when_sequences_match():
    for rule in ("proto-frames", "proto-exact-read"):
        assert findings_for(GRANTN_SOURCES, rule) == []


def test_proto_grantn_exchange_fires_when_server_reverts_to_flat_grants():
    # Version-skew drift: a coordinator answering a REQN with the legacy
    # flat grant list (no GRANTN group header) must be caught.
    skewed = dict(GRANTN_SOURCES)
    skewed[PROTO_SERVER] = GRANTN_SERVER_SRC.replace(
        "        writer.write(proto.LEASE_GRANTN.pack(1, count))\n", "")
    found = findings_for(skewed, "proto-frames")
    assert len(found) == 1
    assert "lease_reqn" in found[0].message
    assert "client awaits [LEASE_GRANTN, U32]" in found[0].message
    assert "server writes [U32]" in found[0].message


def test_proto_grantn_exchange_fires_when_client_sends_wrong_struct():
    # A client still speaking the legacy flat lease want (a bare u32
    # struct, 4 bytes vs REQN's 8) at the batched endpoint.
    skewed = dict(GRANTN_SOURCES)
    skewed[PROTO_CLIENT] = GRANTN_CLIENT_SRC.replace(
        "proto.LEASE_REQN.pack(max_count, width)",
        "proto.GRANT_WANT.pack(max_count)")
    found = findings_for(skewed, "proto-frames")
    assert len(found) == 1
    assert "lease_reqn" in found[0].message
    assert "client sends [GRANT_WANT]" in found[0].message
    assert "server reads [LEASE_REQN]" in found[0].message


def test_proto_grantn_exchange_skipped_when_one_side_absent():
    one_sided = {PROTO_MOD: GRANTN_PROTO_SRC,
                 PROTO_CLIENT: GRANTN_CLIENT_SRC}
    assert findings_for(one_sided, "proto-frames") == []


# The ring exchange (SESSION_EXCHANGES entry "ring_req"): the sharded
# control plane's skew probe, another exchange inside the session
# stream — RING_REQ (the client's ring version) out, RING_INFO (the
# authoritative version + slice identity) back.
RING_PROTO_SRC = '''
import struct

SESSION_FRAME = struct.Struct("<BHI")
SESSION_FRAME_WIRE_SIZE = SESSION_FRAME.size
RING_REQ = struct.Struct("<I")
RING_REQ_WIRE_SIZE = RING_REQ.size
RING_INFO = struct.Struct("<III")
RING_INFO_WIRE_SIZE = RING_INFO.size
REDIRECT = struct.Struct("<II")
REDIRECT_WIRE_SIZE = REDIRECT.size
'''

RING_CLIENT_SRC = '''
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.net.framing import recv_exact, send_all

class DistributerSession:
    def ring_info(self, sock, client_version):
        send_all(sock, proto.SESSION_FRAME.pack(0x08, 0,
                                                proto.RING_REQ_WIRE_SIZE))
        send_all(sock, proto.RING_REQ.pack(client_version))
        hdr = recv_exact(sock, proto.SESSION_FRAME_WIRE_SIZE)
        raw = recv_exact(sock, proto.RING_INFO_WIRE_SIZE)
        return proto.RING_INFO.unpack(raw)
'''

RING_SERVER_SRC = '''
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.net.framing import read_exact

class Distributer:
    async def _session_ring_req(self, reader, writer, seq):
        raw = await read_exact(reader, proto.RING_REQ_WIRE_SIZE)
        (client_version,) = proto.RING_REQ.unpack(raw)
        writer.write(proto.SESSION_FRAME.pack(0x09, seq,
                                              proto.RING_INFO_WIRE_SIZE))
        writer.write(proto.RING_INFO.pack(1, 0, 1))
'''

RING_SOURCES = {PROTO_MOD: RING_PROTO_SRC,
                PROTO_CLIENT: RING_CLIENT_SRC,
                PROTO_SERVER: RING_SERVER_SRC}


def test_proto_ring_exchange_clean_when_sequences_match():
    for rule in ("proto-frames", "proto-exact-read"):
        assert findings_for(RING_SOURCES, rule) == []


def test_proto_ring_exchange_fires_when_server_answers_redirect():
    # Version-skew drift: a coordinator answering the skew probe with a
    # REDIRECT payload (8 bytes) where the client awaits RING_INFO (12)
    # must be caught as a sequence mismatch.
    skewed = dict(RING_SOURCES)
    skewed[PROTO_SERVER] = RING_SERVER_SRC.replace(
        "proto.RING_INFO.pack(1, 0, 1)", "proto.REDIRECT.pack(0, 1)")
    found = findings_for(skewed, "proto-frames")
    assert len(found) == 1
    assert "ring_req" in found[0].message
    assert "client awaits [RING_INFO]" in found[0].message
    assert "server writes [REDIRECT]" in found[0].message


def test_proto_ring_exchange_fires_when_client_sends_wrong_struct():
    # A client pushing a REDIRECT body (8 bytes) into the 4-byte
    # RING_REQ slot — the misroute-chasing code path leaking into the
    # skew probe.
    skewed = dict(RING_SOURCES)
    skewed[PROTO_CLIENT] = RING_CLIENT_SRC.replace(
        "proto.RING_REQ.pack(client_version)",
        "proto.REDIRECT.pack(client_version, 0)")
    found = findings_for(skewed, "proto-frames")
    assert len(found) == 1
    assert "ring_req" in found[0].message
    assert "client sends [REDIRECT]" in found[0].message
    assert "server reads [RING_REQ]" in found[0].message


def test_proto_ring_exchange_skipped_when_one_side_absent():
    one_sided = {PROTO_MOD: RING_PROTO_SRC,
                 PROTO_SERVER: RING_SERVER_SRC}
    assert findings_for(one_sided, "proto-frames") == []


# -- res -------------------------------------------------------------------

def test_res_thread_join_fires_on_unjoined_handleless_thread():
    src = '''
import threading

class R:
    def start(self):
        t = threading.Thread(target=self._run)
        t.start()
        threading.Thread(target=self._pump).start()
'''
    found = findings_for({f"{P}/worker/r.py": src}, "res-thread-join")
    assert len(found) == 2
    assert any("no handle" in f.message for f in found)


def test_res_thread_join_clean_on_daemon_join_and_list_join():
    src = '''
import threading

class R:
    def start(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()
        self._workers = [threading.Thread(target=self._pump)
                         for _ in range(4)]

    def stop(self):
        for t in self._workers:
            t.join()
'''
    assert findings_for({f"{P}/worker/r.py": src}, "res-thread-join") == []


def test_res_socket_close_fires_and_clean_variants():
    fire = '''
import socket

class C:
    def connect(self, addr):
        sock = socket.create_connection(addr)
        sock.sendall(b"x")
'''
    clean = '''
import socket

class C:
    def connect(self, addr):
        self.sock = socket.create_connection(addr)

    def probe(self, addr):
        sock = socket.create_connection(addr)
        try:
            sock.sendall(b"x")
        finally:
            sock.close()
'''
    found = findings_for({f"{P}/net/c.py": fire}, "res-socket-close")
    assert len(found) == 1
    assert "never closed" in found[0].message
    assert findings_for({f"{P}/net/c.py": clean}, "res-socket-close") == []


def test_res_queue_unbounded_fires_only_without_maxsize():
    src = '''
import queue

class Q:
    def __init__(self):
        self._work = queue.Queue()
        self._done = queue.Queue(maxsize=8)
'''
    found = findings_for({f"{P}/worker/q.py": src}, "res-queue-unbounded")
    assert len(found) == 1
    assert found[0].severity == "warning"


def test_res_shutdown_fires_without_stop_hook():
    src = '''
from concurrent.futures import ThreadPoolExecutor

class S:
    def __init__(self):
        self.pool = ThreadPoolExecutor(max_workers=2)
'''
    found = findings_for({f"{P}/coordinator/s.py": src}, "res-shutdown")
    assert len(found) == 1
    assert "shutdown" in found[0].message
    healed = src + '''
    def close(self):
        self.pool.shutdown(wait=False)
'''
    assert findings_for({f"{P}/coordinator/s.py": healed},
                        "res-shutdown") == []


def test_res_rules_skip_out_of_scope_dirs():
    src = '''
import queue

class Q:
    def __init__(self):
        self._work = queue.Queue()
'''
    assert findings_for({f"{P}/core/q.py": src}, "res-queue-unbounded") == []


# -- obs-name --------------------------------------------------------------

NAMES_MOD = f"{P}/obs/names.py"
NAMES_SRC = '''
TILES_DONE = "tiles_done"

LEGACY_ALIASES: dict[str, str] = {TILES_DONE: "tiles_complete"}
'''


def test_obs_name_fires_on_unregistered_literal():
    src = '''
class W:
    def f(self):
        self.counters.inc("tiles_done")
        self.counters.inc("tiles_complete")
        self.counters.inc("tils_done")
        self.conf.get("not_a_metric")
'''
    found = findings_for({NAMES_MOD: NAMES_SRC, f"{P}/worker/w.py": src},
                         "obs-name")
    assert len(found) == 1
    assert "'tils_done'" in found[0].message
    # Without a names module there is no arbiter — stay silent.
    assert findings_for({f"{P}/worker/w.py": src}, "obs-name") == []


def test_obs_name_covers_span_recorder_sites():
    src = '''
class W:
    def f(self):
        self.spans.record("not_registered", 0, 1.0, 2.0)
'''
    found = findings_for({NAMES_MOD: NAMES_SRC, f"{P}/worker/w.py": src},
                         "obs-name")
    assert len(found) == 1


# -- obs-dead --------------------------------------------------------------

DEAD_NAMES_SRC = '''
TILES_DONE = "tiles_done"
GHOST_DEPTH = "ghost_depth"

LEGACY_ALIASES: dict[str, str] = {TILES_DONE: "tiles_complete"}
'''


def test_obs_dead_fires_on_uninstrumented_registration():
    src = '''
class W:
    def f(self):
        self.counters.inc("tiles_done")
'''
    found = findings_for({NAMES_MOD: DEAD_NAMES_SRC,
                          f"{P}/worker/w.py": src}, "obs-dead")
    assert len(found) == 1
    assert "GHOST_DEPTH" in found[0].message
    assert found[0].path == NAMES_MOD  # anchored at the registration


def test_obs_dead_clean_when_referenced_by_attribute_or_literal():
    src = f'''
from {P}.obs import names as obs_names


class W:
    def f(self):
        self.counters.inc("tiles_done")
        self.gauges.set(obs_names.GHOST_DEPTH, 2)
'''
    assert findings_for({NAMES_MOD: DEAD_NAMES_SRC,
                         f"{P}/worker/w.py": src}, "obs-dead") == []


# -- obs-event -------------------------------------------------------------

EVENTS_MOD = f"{P}/obs/events.py"
EVENTS_SRC = '''
SCHED_GRANT = "sched.grant"
CKPT_DONE = "ckpt.done"
'''


def test_obs_event_fires_on_unregistered_literal():
    src = '''
from distributedmandelbrot_tpu.obs import flight


def f(self):
    flight.note("sched.grant")
    flight.note("sched.grnat")
    self.notebook.note("not.an.event")
'''
    found = findings_for({EVENTS_MOD: EVENTS_SRC,
                          f"{P}/coordinator/s.py": src}, "obs-event")
    # one unregistered emit + CKPT_DONE registered-but-never-emitted
    assert len(found) == 2
    assert any("'sched.grnat'" in f.message for f in found)
    # Without an events module there is no arbiter — stay silent.
    assert findings_for({f"{P}/coordinator/s.py": src}, "obs-event") == []


def test_obs_event_reverse_audit_accepts_attr_and_import_refs():
    src = f'''
from {P}.obs import events as obs_events
from {P}.obs import flight


def f():
    flight.note(obs_events.SCHED_GRANT)
    flight.note("ckpt.done")
'''
    assert findings_for({EVENTS_MOD: EVENTS_SRC,
                         f"{P}/coordinator/s.py": src}, "obs-event") == []


def test_obs_event_reverse_audit_fires_on_ghost_event():
    src = '''
from distributedmandelbrot_tpu.obs import flight


def f():
    flight.note("sched.grant")
'''
    found = findings_for({EVENTS_MOD: EVENTS_SRC,
                          f"{P}/coordinator/s.py": src}, "obs-event")
    assert len(found) == 1
    assert "CKPT_DONE" in found[0].message
    assert found[0].path == EVENTS_MOD  # anchored at the registration


# -- fsm: protocol state machines ------------------------------------------

FSM_CLIENT_REL = f"{P}/viewer/client.py"
FSM_SERVER_REL = f"{P}/coordinator/dataserver.py"

FSM_QUERY_CLIENT = f'''
from {P}.net import framing
from {P}.net import protocol as proto


class DataClient:
    def _fetch_once(self, sock, level, ir, ii):
        framing.send_all(sock, proto.QUERY.pack(level, ir, ii))
        status = framing.recv_byte(sock)
        if status == proto.QUERY_REJECT:
            return None
        if status != proto.QUERY_ACCEPT:
            raise framing.ProtocolError("bad status")
        return b"tile"
'''

FSM_QUERY_SERVER = f'''
from {P}.net import framing
from {P}.net import protocol as proto


class DataServer:
    def _handle_connection(self, conn):
        level, ir, ii = proto.QUERY.unpack(
            framing.recv_exact(conn, proto.QUERY.size))
        if self._have(level, ir, ii):
            framing.send_byte(conn, proto.QUERY_ACCEPT)
        else:
            framing.send_byte(conn, proto.QUERY_REJECT)

    def _have(self, level, ir, ii):
        return True
'''


def test_fsm_dual_fires_on_send_without_receive_arm():
    # The client piggybacks a RENDER_QUERY_TAIL the server never reads.
    client = FSM_QUERY_CLIENT.replace(
        "        status = framing.recv_byte(sock)",
        "        framing.send_all(sock, proto.RENDER_QUERY_TAIL.pack(0, 0))\n"
        "        status = framing.recv_byte(sock)")
    found = findings_for({FSM_CLIENT_REL: client,
                          FSM_SERVER_REL: FSM_QUERY_SERVER}, "fsm-dual")
    assert found
    assert "RENDER_QUERY_TAIL" in found[0].message


def test_fsm_dual_clean_on_matched_pair():
    assert findings_for({FSM_CLIENT_REL: FSM_QUERY_CLIENT,
                         FSM_SERVER_REL: FSM_QUERY_SERVER}, "fsm-dual") == []


def test_fsm_dead_arm_fires_on_branch_no_config_reaches():
    # Server can only ever accept, so the client's REJECT arm is dead.
    server = FSM_QUERY_SERVER.replace(
        """        if self._have(level, ir, ii):
            framing.send_byte(conn, proto.QUERY_ACCEPT)
        else:
            framing.send_byte(conn, proto.QUERY_REJECT)""",
        "        framing.send_byte(conn, proto.QUERY_ACCEPT)")
    found = findings_for({FSM_CLIENT_REL: FSM_QUERY_CLIENT,
                          FSM_SERVER_REL: server}, "fsm-dead-arm")
    assert len(found) == 1
    assert "QUERY_REJECT" in found[0].message
    assert found[0].path == FSM_CLIENT_REL


def test_fsm_dead_arm_clean_when_both_branches_reachable():
    assert findings_for({FSM_CLIENT_REL: FSM_QUERY_CLIENT,
                         FSM_SERVER_REL: FSM_QUERY_SERVER},
                        "fsm-dead-arm") == []


FSM_SESSION_CLIENT_REL = f"{P}/worker/client.py"
FSM_SESSION_SERVER_REL = f"{P}/coordinator/distributer.py"

# The gate test on the send is what separates fire from no-fire below.
FSM_SESSION_CLIENT_GUARDED = f'''
from {P}.net import framing
from {P}.net import protocol as proto


class DistributerSession:
    def connect(self):
        framing.send_byte(self._sock, proto.PURPOSE_SESSION)
        return True

    def upload(self, seq):
        framing.send_all(
            self._sock,
            proto.SESSION_FRAME.pack(proto.FRAME_UPLOAD, seq, 0))

    def send_spans(self, seq):
        if self.flags & proto.SESSION_FLAG_RLE:
            framing.send_all(
                self._sock,
                proto.SESSION_FRAME.pack(proto.FRAME_SPANS, seq, 0))
'''

FSM_SESSION_SERVER_GATED = f'''
from {P}.net import framing
from {P}.net import protocol as proto


class Distributer:
    async def _handle_session(self, reader, writer):
        while True:
            try:
                frame_type, seq, length = proto.SESSION_FRAME.unpack(
                    await framing.read_exact(
                        reader, proto.SESSION_FRAME.size))
            except ConnectionError:
                return
            if frame_type == proto.FRAME_UPLOAD:
                continue
            if self.caps & proto.SESSION_FLAG_RLE:
                if frame_type == proto.FRAME_SPANS:
                    continue
            raise framing.ProtocolError("unexpected frame")
'''


def test_fsm_cap_gate_fires_on_unguarded_send():
    client = FSM_SESSION_CLIENT_GUARDED.replace(
        """        if self.flags & proto.SESSION_FLAG_RLE:
            framing.send_all(
                self._sock,
                proto.SESSION_FRAME.pack(proto.FRAME_SPANS, seq, 0))""",
        """        framing.send_all(
            self._sock,
            proto.SESSION_FRAME.pack(proto.FRAME_SPANS, seq, 0))""")
    found = findings_for({FSM_SESSION_CLIENT_REL: client,
                          FSM_SESSION_SERVER_REL: FSM_SESSION_SERVER_GATED},
                         "fsm-cap-gate")
    assert found
    assert "RLE" in found[0].message


def test_fsm_cap_gate_clean_when_send_guarded_by_same_cap():
    sources = {FSM_SESSION_CLIENT_REL: FSM_SESSION_CLIENT_GUARDED,
               FSM_SESSION_SERVER_REL: FSM_SESSION_SERVER_GATED}
    assert findings_for(sources, "fsm-cap-gate") == []


def test_fsm_deadlock_fires_on_desynced_fixture():
    # One send, two reads: the product wedges with both sides waiting.
    server = FSM_QUERY_SERVER.replace(
        "        level, ir, ii = proto.QUERY.unpack(\n"
        "            framing.recv_exact(conn, proto.QUERY.size))",
        "        level, ir, ii = proto.QUERY.unpack(\n"
        "            framing.recv_exact(conn, proto.QUERY.size))\n"
        "        level, ir, ii = proto.QUERY.unpack(\n"
        "            framing.recv_exact(conn, proto.QUERY.size))")
    found = findings_for({FSM_CLIENT_REL: FSM_QUERY_CLIENT,
                          FSM_SERVER_REL: server}, "fsm-deadlock")
    assert found
    assert "client@" in found[0].message and "server@" in found[0].message


# -- engine: suppressions, baseline, reporters -----------------------------

def test_inline_suppression_same_line_and_line_above():
    same_line = LOCK_CLASS.replace(
        "        self._items.pop(k, None)",
        "        self._items.pop(k, None)  # dmtpu: ignore[lock-guard] ok")
    line_above = LOCK_CLASS.replace(
        "        self._items.pop(k, None)",
        "        # dmtpu: ignore[lock-guard] single-threaded teardown\n"
        "        self._items.pop(k, None)")
    for src in (same_line, line_above):
        report = run_check(Project.from_sources({LOCK_GUARD_FIRE: src}))
        assert report.clean
        assert [f.rule for f in report.suppressed] == ["lock-guard"]


def test_suppression_wildcard_and_wrong_rule():
    wildcard = LOCK_CLASS.replace(
        "        self._items.pop(k, None)",
        "        self._items.pop(k, None)  # dmtpu: ignore[*]")
    wrong = LOCK_CLASS.replace(
        "        self._items.pop(k, None)",
        "        self._items.pop(k, None)  # dmtpu: ignore[wire-literal]")
    assert run_check(Project.from_sources({LOCK_GUARD_FIRE: wildcard})).clean
    report = run_check(Project.from_sources({LOCK_GUARD_FIRE: wrong}))
    assert [f.rule for f in report.findings] == ["lock-guard"]


def test_baseline_grandfathers_and_reports_stale(tmp_path):
    project = Project.from_sources({LOCK_GUARD_FIRE: LOCK_CLASS})
    finding = check_project(project, ["lock-guard"])[0]
    path = tmp_path / "baseline.json"
    analysis.save_baseline(path, [finding])
    baseline = analysis.load_baseline(path)
    report = run_check(project, baseline=baseline)
    assert report.clean
    assert [f.fingerprint() for f in report.baselined] == sorted(baseline)
    # An entry matching nothing is stale and must be reported.
    report = run_check(Project.from_sources({}), baseline={"gone::x.py::y"})
    assert report.stale_baseline == ["gone::x.py::y"]


def test_baseline_survives_line_drift():
    project = Project.from_sources(
        {LOCK_GUARD_FIRE: "# a new leading comment\n" + LOCK_CLASS})
    shifted = check_project(project, ["lock-guard"])[0]
    original = check_project(
        Project.from_sources({LOCK_GUARD_FIRE: LOCK_CLASS}),
        ["lock-guard"])[0]
    assert shifted.line != original.line
    assert shifted.fingerprint() == original.fingerprint()


def test_parse_error_reported_as_finding():
    report = run_check(Project.from_sources(
        {f"{P}/serve/broken.py": "def f(:\n"}))
    assert [f.rule for f in report.findings] == ["parse-error"]
    assert report.findings[0].severity == "error"


def test_json_report_schema():
    report = run_check(Project.from_sources({LOCK_GUARD_FIRE: LOCK_CLASS}))
    doc = json.loads(analysis.render_json(report))
    assert doc["version"] == 1
    assert set(doc["counts"]) == {"error", "warning", "total",
                                  "suppressed", "baselined"}
    assert doc["counts"]["total"] == len(doc["findings"]) == 1
    assert set(doc["findings"][0]) == {"rule", "severity", "path",
                                       "line", "message"}
    assert doc["stale_baseline"] == []


def test_text_report_format_is_clickable():
    report = run_check(Project.from_sources({LOCK_GUARD_FIRE: LOCK_CLASS}))
    line = analysis.render_text(report).splitlines()[0]
    assert line.startswith(f"{LOCK_GUARD_FIRE}:")
    assert ": error: [lock-guard]" in line


# -- CLI: --update-baseline round trip -------------------------------------

def test_cli_update_baseline_round_trip(tmp_path, capsys):
    from distributedmandelbrot_tpu.cli import main
    pkg = tmp_path / P / "serve"
    pkg.mkdir(parents=True)
    (pkg / "stateful.py").write_text(LOCK_CLASS)
    baseline = tmp_path / "baseline.json"

    # Dirty tree exits 1...
    assert main(["check", "--root", str(tmp_path),
                 "--baseline", str(baseline)]) == 1
    # ...--update-baseline grandfathers it...
    assert main(["check", "--root", str(tmp_path),
                 "--baseline", str(baseline), "--update-baseline"]) == 0
    # ...after which the same tree is clean and the entry is live (not
    # stale).
    assert main(["check", "--root", str(tmp_path),
                 "--baseline", str(baseline), "--json"]) == 0
    out = capsys.readouterr().out
    doc = json.loads(out[out.index('{'):])
    assert doc["counts"]["baselined"] == 1
    assert doc["stale_baseline"] == []


# -- CLI: --diff <git-ref> -------------------------------------------------

def test_cli_diff_reports_only_findings_since_ref(tmp_path, capsys):
    import shutil
    import subprocess

    if shutil.which("git") is None:
        pytest.skip("git not available")
    from distributedmandelbrot_tpu.cli import main

    pkg = tmp_path / P / "serve"
    pkg.mkdir(parents=True)
    (pkg / "stateful.py").write_text(LOCK_CLASS)

    def git(*argv):
        subprocess.run(
            ["git", "-C", str(tmp_path),
             "-c", "user.email=ci@example.invalid", "-c", "user.name=ci",
             *argv], check=True, capture_output=True)

    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "seed")

    baseline = tmp_path / "baseline.json"
    # Without --diff the pre-existing finding is reported...
    assert main(["check", "--root", str(tmp_path),
                 "--baseline", str(baseline)]) == 1
    # ...with --diff HEAD it is an ephemeral baseline entry, not stale.
    assert main(["check", "--root", str(tmp_path),
                 "--baseline", str(baseline), "--diff", "HEAD",
                 "--json"]) == 0
    out = capsys.readouterr().out
    doc = json.loads(out[out.index('{'):])
    assert doc["counts"]["total"] == 0
    assert doc["counts"]["baselined"] == 1
    assert doc["stale_baseline"] == []

    # A finding introduced after the ref is the only one reported.
    (pkg / "fresh.py").write_text(LOCK_CLASS.replace("Cache", "Fresh"))
    assert main(["check", "--root", str(tmp_path),
                 "--baseline", str(baseline), "--diff", "HEAD",
                 "--json"]) == 1
    out = capsys.readouterr().out
    doc = json.loads(out[out.index('{'):])
    assert doc["counts"]["total"] == 1
    assert doc["findings"][0]["path"].endswith("fresh.py")


def test_cli_diff_bad_ref_exits_2(tmp_path, capsys):
    import shutil

    if shutil.which("git") is None:
        pytest.skip("git not available")
    from distributedmandelbrot_tpu.cli import main

    (tmp_path / P).mkdir()
    assert main(["check", "--root", str(tmp_path),
                 "--diff", "no-such-ref"]) == 2


# -- taint: wire input reaching dangerous sinks ----------------------------

TAINT_FILE = f"{P}/coordinator/handler.py"

TAINT_LOOP_FIRE = '''
from distributedmandelbrot_tpu.net import framing


def handle(sock):
    n = framing.recv_u32(sock)
    out = []
    for _ in range(n):
        out.append(framing.recv_byte(sock))
    return out
'''


def test_taint_loop_fires_on_wire_range_bound():
    found = findings_for({TAINT_FILE: TAINT_LOOP_FIRE}, "taint-loop")
    assert len(found) == 1
    assert found[0].severity == "error"
    assert "range() bound" in found[0].message


def test_taint_loop_clean_after_validate_call():
    src = TAINT_LOOP_FIRE.replace(
        "    n = framing.recv_u32(sock)",
        "    n = validate_count(framing.recv_u32(sock), 4096)")
    assert findings_for({TAINT_FILE: src}, "taint-loop") == []


def test_taint_loop_clean_after_comparison_guard():
    src = TAINT_LOOP_FIRE.replace(
        "    out = []",
        "    if n > 4096:\n        raise ValueError(n)\n    out = []")
    assert findings_for({TAINT_FILE: src}, "taint-loop") == []


def test_taint_loop_clean_after_min_clamp():
    src = TAINT_LOOP_FIRE.replace(
        "    out = []",
        "    n = min(n, 4096)\n    out = []")
    assert findings_for({TAINT_FILE: src}, "taint-loop") == []


TAINT_ALLOC_FIRE = '''
from distributedmandelbrot_tpu.net import framing


def read_payload(sock):
    length = framing.recv_u32(sock)
    return framing.recv_exact(sock, length)
'''


def test_taint_alloc_fires_on_wire_sized_read():
    found = findings_for({TAINT_FILE: TAINT_ALLOC_FIRE}, "taint-alloc")
    assert len(found) == 1
    assert "recv_exact" in found[0].message


def test_taint_alloc_fires_on_bytearray():
    src = TAINT_ALLOC_FIRE.replace(
        "    return framing.recv_exact(sock, length)",
        "    return bytearray(length)")
    found = findings_for({TAINT_FILE: src}, "taint-alloc")
    assert len(found) == 1
    assert "bytearray" in found[0].message


def test_taint_alloc_clean_after_payload_validator():
    src = TAINT_ALLOC_FIRE.replace(
        "    length = framing.recv_u32(sock)",
        "    length = validate_payload_length(framing.recv_u32(sock))")
    assert findings_for({TAINT_FILE: src}, "taint-alloc") == []


TAINT_INDEX_FIRE = '''
from distributedmandelbrot_tpu.net import framing


def lookup(sock, table):
    i = framing.recv_u32(sock)
    return table[i]
'''


def test_taint_index_fires_on_wire_subscript():
    found = findings_for({TAINT_FILE: TAINT_INDEX_FIRE}, "taint-index")
    assert len(found) == 1
    assert "container index" in found[0].message


def test_taint_index_clean_after_len_guard():
    src = TAINT_INDEX_FIRE.replace(
        "    return table[i]",
        "    if i >= len(table):\n        return None\n    return table[i]")
    assert findings_for({TAINT_FILE: src}, "taint-index") == []


TAINT_STRUCT_FIRE = '''
import struct

from distributedmandelbrot_tpu.net import framing


def read_array(sock):
    n = framing.recv_u32(sock)
    data = framing.recv_exact(sock, 4)
    return struct.unpack(f"<{n}I", data)
'''


def test_taint_struct_fires_on_wire_repeat_count():
    found = findings_for({TAINT_FILE: TAINT_STRUCT_FIRE}, "taint-struct")
    assert len(found) == 1
    assert "format" in found[0].message


def test_taint_struct_clean_with_constant_format():
    src = TAINT_STRUCT_FIRE.replace('f"<{n}I"', '"<4I"')
    assert findings_for({TAINT_FILE: src}, "taint-struct") == []


# Through-helper flows: the call graph carries taint across functions in
# both directions — a helper's tainted RETURN reaches the caller's sink,
# and a tainted ARGUMENT reaches the helper's sink.

TAINT_HELPER_RETURN = '''
import struct


class Handler:
    async def _read_len(self, reader):
        data = await reader.readexactly(4)
        (n,) = struct.unpack("<I", data)
        return n

    async def handle(self, reader):
        n = await self._read_len(reader)
        for _ in range(n):
            await reader.readexactly(16)
'''


def test_taint_flows_through_helper_return_via_callgraph():
    found = findings_for({TAINT_FILE: TAINT_HELPER_RETURN}, "taint-loop")
    assert len(found) == 1
    assert "range() bound" in found[0].message


TAINT_HELPER_PARAM = '''
from distributedmandelbrot_tpu.net import framing


class Handler:
    def _alloc(self, n):
        return bytearray(n)

    def handle(self, sock):
        n = framing.recv_u32(sock)
        return self._alloc(n)
'''


def test_taint_flows_into_helper_param_via_callgraph():
    found = findings_for({TAINT_FILE: TAINT_HELPER_PARAM}, "taint-alloc")
    assert len(found) == 1
    assert "_alloc" in found[0].message


def test_taint_helper_param_clean_when_sanitized_before_call():
    src = TAINT_HELPER_PARAM.replace(
        "        return self._alloc(n)",
        "        n = validate_count(n, 4096)\n        return self._alloc(n)")
    assert findings_for({TAINT_FILE: src}, "taint-alloc") == []


def test_taint_out_of_scope_dirs_are_ignored():
    # storage/ only sees validated data; same source there is clean.
    assert findings_for({f"{P}/storage/handler.py": TAINT_LOOP_FIRE},
                        "taint-loop") == []


# -- exc: exception-path leaks and silent swallows -------------------------

EXC_FILE = f"{P}/coordinator/ingest.py"

EXC_LEAK_FIRE = '''
from distributedmandelbrot_tpu.net import framing


class Ingest:
    async def ingest(self, reader, writer, w):
        token = self.scheduler.claim(w)
        if token is None:
            return
        framing.write_byte(writer, 0x20)
        await writer.drain()
        try:
            data = await framing.read_exact(reader, 16)
        except ConnectionError:
            self.scheduler.release_claim(w, token)
            raise
        self.scheduler.finish_claim(w, token)
'''


def test_exc_leak_fires_on_io_between_claim_and_try():
    found = findings_for({EXC_FILE: EXC_LEAK_FIRE}, "exc-leak")
    assert len(found) == 1
    assert found[0].severity == "error"
    assert "token" in found[0].message


def test_exc_leak_clean_when_io_moved_inside_guarded_try():
    src = EXC_LEAK_FIRE.replace(
        "        framing.write_byte(writer, 0x20)\n"
        "        await writer.drain()\n"
        "        try:\n"
        "            data = await framing.read_exact(reader, 16)",
        "        try:\n"
        "            framing.write_byte(writer, 0x20)\n"
        "            await writer.drain()\n"
        "            data = await framing.read_exact(reader, 16)")
    assert findings_for({EXC_FILE: src}, "exc-leak") == []


def test_exc_leak_clean_when_finally_releases():
    src = '''
class Ingest:
    async def ingest(self, writer, w):
        token = self.scheduler.claim(w)
        try:
            await writer.drain()
        finally:
            self.scheduler.release_claim(w, token)
'''
    assert findings_for({EXC_FILE: src}, "exc-leak") == []


def test_exc_leak_socket_fires_on_io_before_close():
    src = '''
import socket


def probe(host):
    sock = socket.create_connection((host, 80))
    sock.sendall(b"ping")
    sock.close()
'''
    found = findings_for({EXC_FILE: src}, "exc-leak")
    assert len(found) == 1
    assert "socket" in found[0].message


def test_exc_leak_socket_clean_when_returned_or_with():
    # Returning transfers ownership (worker client's _connect shape);
    # non-I/O setup calls in between are fine.
    src = '''
import socket


def dial(host):
    sock = socket.create_connection((host, 80))
    sock.setsockopt(1, 2, 3)
    return sock
'''
    assert findings_for({EXC_FILE: src}, "exc-leak") == []


def test_exc_swallow_fires_on_silent_overbroad_handler():
    src = '''
def best_effort(fn):
    try:
        fn()
    except Exception:
        pass
'''
    found = findings_for({EXC_FILE: src}, "exc-swallow")
    assert len(found) == 1
    assert found[0].severity == "warning"


def test_exc_swallow_clean_when_logged_counted_or_narrow():
    src = '''
import logging

logger = logging.getLogger(__name__)


def logged(fn):
    try:
        fn()
    except Exception:
        logger.debug("probe failed", exc_info=True)


def counted(fn, counters):
    try:
        fn()
    except Exception:
        counters.inc("probe_failures")


def narrow(fn):
    try:
        fn()
    except ValueError:
        pass
'''
    assert findings_for({EXC_FILE: src}, "exc-swallow") == []


def test_exc_swallow_clean_when_exception_bound_and_used():
    # The embed.py shape: the handler stores the exception for a later
    # re-raise — that is handling, not swallowing.
    src = '''
class Runner:
    def run(self, fn):
        try:
            fn()
        except BaseException as e:
            self._error = e
'''
    assert findings_for({EXC_FILE: src}, "exc-swallow") == []


# -- CLI: --severity and comma-separated --rules ---------------------------

def test_cli_severity_filter(tmp_path, capsys):
    from distributedmandelbrot_tpu.cli import main
    pkg = tmp_path / P / "coordinator"
    pkg.mkdir(parents=True)
    # One error (taint-loop) + one warning (exc-swallow).
    (pkg / "handler.py").write_text(
        TAINT_LOOP_FIRE
        + "\n\ndef quiet(fn):\n    try:\n        fn()\n"
          "    except Exception:\n        pass\n")
    baseline = tmp_path / "baseline.json"

    assert main(["check", "--root", str(tmp_path), "--baseline",
                 str(baseline), "--json"]) == 1
    out = capsys.readouterr().out
    doc = json.loads(out[out.index('{'):])
    assert doc["counts"]["error"] == 1
    assert doc["counts"]["warning"] == 1

    assert main(["check", "--root", str(tmp_path), "--baseline",
                 str(baseline), "--severity", "error", "--json"]) == 1
    out = capsys.readouterr().out
    doc = json.loads(out[out.index('{'):])
    assert doc["counts"]["total"] == 1
    assert doc["findings"][0]["rule"] == "taint-loop"


def test_cli_rules_accepts_comma_separated_families(tmp_path, capsys):
    from distributedmandelbrot_tpu.cli import main
    pkg = tmp_path / P / "coordinator"
    pkg.mkdir(parents=True)
    (pkg / "handler.py").write_text(TAINT_LOOP_FIRE)
    baseline = tmp_path / "baseline.json"

    assert main(["check", "--root", str(tmp_path), "--baseline",
                 str(baseline), "--rules", "taint,exc", "--json"]) == 1
    out = capsys.readouterr().out
    doc = json.loads(out[out.index('{'):])
    assert {f["rule"] for f in doc["findings"]} == {"taint-loop"}
    # Families outside the selection are filtered even if they'd fire.
    assert main(["check", "--root", str(tmp_path), "--baseline",
                 str(baseline), "--rules", "exc,res", "--json"]) == 0


# -- CLI: --diff with a file deleted since the ref -------------------------

def test_cli_diff_survives_deleted_file(tmp_path, capsys):
    import shutil
    import subprocess

    if shutil.which("git") is None:
        pytest.skip("git not available")
    from distributedmandelbrot_tpu.cli import main

    pkg = tmp_path / P / "serve"
    pkg.mkdir(parents=True)
    (pkg / "stateful.py").write_text(LOCK_CLASS)
    (pkg / "doomed.py").write_text(LOCK_CLASS.replace("Cache", "Doomed"))

    def git(*argv):
        subprocess.run(
            ["git", "-C", str(tmp_path),
             "-c", "user.email=ci@example.invalid", "-c", "user.name=ci",
             *argv], check=True, capture_output=True)

    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "seed")

    # Delete a file that had findings at the ref: its ref fingerprints
    # match nothing now, and --diff must treat that as expected churn
    # (rc 0, no stale entries, no crash), not a lookup error.
    (pkg / "doomed.py").unlink()
    baseline = tmp_path / "baseline.json"
    assert main(["check", "--root", str(tmp_path),
                 "--baseline", str(baseline), "--diff", "HEAD",
                 "--json"]) == 0
    out = capsys.readouterr().out
    doc = json.loads(out[out.index('{'):])
    assert doc["counts"]["total"] == 0
    assert doc["stale_baseline"] == []
