"""Telemetry subsystem: registry/histograms, Prometheus rendering, the
HTTP exporter, the tile-lifecycle trace, and the legacy Counters shim."""

import importlib.util
import json
import math
import os
import threading
import urllib.error
import urllib.request

import pytest

from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.exporter import render_prometheus
from distributedmandelbrot_tpu.obs.metrics import DEFAULT_BUCKETS, Registry
from distributedmandelbrot_tpu.obs.trace import TraceLog
from distributedmandelbrot_tpu.utils.metrics import Counters


def _load_check_metrics():
    """tools/ is not a package; import the validator straight off disk so
    the suite and the standalone tool can never diverge."""
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "check_metrics.py")
    spec = importlib.util.spec_from_file_location("check_metrics", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- histograms ------------------------------------------------------------


def test_histogram_bucket_boundaries():
    reg = Registry()
    h = reg.histogram("h")
    assert h.bounds == tuple(sorted(DEFAULT_BUCKETS))
    h.observe(DEFAULT_BUCKETS[0])       # exactly on a bound: that bucket
    h.observe(DEFAULT_BUCKETS[0] * 1.5)  # strictly inside the next
    h.observe(0.0)                       # below every bound: first bucket
    h.observe(1e9)                       # past the last bound: overflow
    assert h.counts[0] == 2
    assert h.counts[1] == 1
    assert h.counts[-1] == 1
    assert h.count == 4
    assert h.sum == pytest.approx(DEFAULT_BUCKETS[0] * 2.5 + 1e9)


def test_histogram_percentiles_interpolate():
    reg = Registry()
    h = reg.histogram("h", buckets=[1.0, 2.0, 4.0])
    assert h.percentile(50) is None  # no observations yet
    for v in (0.5, 1.5, 2.5, 3.5):
        h.observe(v)
    # rank(p50) = 2: one obs <= 1.0, the second closes the (1, 2] bucket.
    assert h.percentile(50) == pytest.approx(2.0)
    assert h.percentile(25) == pytest.approx(1.0)
    # p100 walks to the last finite bound.
    assert h.percentile(100) == pytest.approx(4.0)


def test_histogram_overflow_reports_last_bound():
    reg = Registry()
    h = reg.histogram("h", buckets=[1.0, 2.0])
    h.observe(50.0)
    # The histogram cannot see past its last boundary; it must say 2.0,
    # not invent a number beyond its resolution.
    assert h.percentile(50) == pytest.approx(2.0)


def test_histogram_family_shares_first_registered_bounds():
    reg = Registry()
    reg.histogram("h", buckets=[1.0, 2.0])
    child = reg.histogram("h", labels={"outcome": "x"},
                          buckets=[7.0, 8.0, 9.0])  # ignored: family bound
    assert child.bounds == (1.0, 2.0)
    reg.observe("h", 0.5)
    reg.observe("h", 1.5, labels={"outcome": "x"})
    assert reg.family_percentile("h", 100) == pytest.approx(2.0)
    assert reg.family_percentile("missing", 50) is None


def test_quantile_from_counts_edges():
    from distributedmandelbrot_tpu.obs.metrics import quantile_from_counts

    bounds = (1.0, 2.0, 4.0)
    # No observations: a timeseries point needs a number, not a gap.
    assert quantile_from_counts(bounds, [], 0.5) == 0.0
    assert quantile_from_counts(bounds, [0, 0, 0], 0.99) == 0.0
    # q >= 1.0 pins to the upper bound of the highest NONEMPTY bucket —
    # interpolation must never manufacture a value past the last bucket
    # the data actually reached.
    assert quantile_from_counts(bounds, [3, 5, 0], 1.0) == 2.0
    assert quantile_from_counts(bounds, [3, 5, 0], 1.5) == 2.0  # clamped
    assert quantile_from_counts(bounds, [1, 0, 0], 1.0) == 1.0
    # Overflow bucket (trailing extra entry) reports bounds[-1]: the
    # histogram cannot see past its last boundary.
    assert quantile_from_counts(bounds, [0, 0, 0, 7], 0.5) == 4.0
    assert quantile_from_counts(bounds, [0, 0, 0, 7], 1.0) == 4.0
    # q <= 0 clamps to 0 and interpolates from the bucket's lower edge.
    assert quantile_from_counts(bounds, [4, 0, 0], -1.0) == 0.0
    # Interpolation inside a bucket: 2 obs in (1, 2], rank(p50)=1 lands
    # halfway through that bucket.
    assert quantile_from_counts(bounds, [0, 2, 0], 0.5) == \
        pytest.approx(1.5)


def test_registry_name_kind_binding_enforced():
    reg = Registry()
    reg.counter("x").inc()
    with pytest.raises(ValueError, match="counter"):
        reg.gauge("x")
    with pytest.raises(ValueError, match="counter"):
        reg.histogram("x")


def test_timed_observes_even_on_exception():
    reg = Registry()
    with pytest.raises(RuntimeError):
        with reg.timed("op_seconds", labels={"outcome": "boom"}):
            raise RuntimeError("boom")
    assert reg.histogram("op_seconds", labels={"outcome": "boom"}).count == 1


def test_callback_gauge_failure_renders_nan_not_crash():
    reg = Registry()
    reg.gauge("broken", fn=lambda: 1 / 0)
    snap = reg.snapshot()
    assert math.isnan(snap["gauges"]["broken"])
    text = render_prometheus(reg)
    assert "broken NaN" in text


def test_registry_thread_safety_under_concurrent_updates():
    reg = Registry()
    n_threads, per_thread = 8, 2000
    start = threading.Barrier(n_threads + 1)

    def writer(i):
        start.wait()
        for k in range(per_thread):
            reg.inc("hits")
            reg.observe("lat", 0.001 * (k % 7),
                        labels={"outcome": str(i % 2)})

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    start.wait()
    # Concurrent readers must see consistent cuts, never raise.
    for _ in range(50):
        snap = reg.snapshot()
        assert snap["counters"].get("hits", 0) <= n_threads * per_thread
        render_prometheus(reg)
    for t in threads:
        t.join()
    assert reg.counter_value("hits") == n_threads * per_thread
    total = sum(h["count"] for label, h in
                reg.snapshot()["histograms"].items() if label.startswith("lat"))
    assert total == n_threads * per_thread


# -- Counters shim ---------------------------------------------------------


def test_counters_get_does_not_mutate():
    c = Counters()
    assert c.get("never_written") == 0
    # The old defaultdict inserted probed keys forever; the shim must not.
    assert "never_written" not in c.snapshot()
    assert c.registry.counter_value("never_written") is None


def test_counters_legacy_alias_reads_sum_canonical():
    c = Counters()
    c.inc(obs_names.WORKER_RESULTS_ACCEPTED, 2)
    c.inc(obs_names.COORD_RESULTS_ACCEPTED, 3)
    c.inc(obs_names.COORD_RESULTS_REJECTED)
    # The legacy spelling reads what a shared pre-split Counters instance
    # would have reported: both sides merged.
    assert c.get("results_accepted") == 5
    assert c.get("results_rejected") == 1
    snap = c.snapshot()
    assert snap["results_accepted"] == 5
    assert snap[obs_names.COORD_RESULTS_ACCEPTED] == 3
    # Exact canonical names always win over the alias path.
    assert c.get(obs_names.WORKER_RESULTS_ACCEPTED) == 2


def test_frame_rejection_counters_are_registered_names():
    # The fuzz suite (test_fuzz_frames.py) asserts these increment on
    # hostile frames; the --names audit (tools/check_metrics.py --names,
    # the obs-name rule) must know them or the handlers would flag.
    import os

    from distributedmandelbrot_tpu.analysis import Project
    from distributedmandelbrot_tpu.analysis import rules_obs
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    known = rules_obs.known_names(Project.from_root(repo))
    assert obs_names.COORD_FRAMES_REJECTED in known
    assert obs_names.GATEWAY_FRAMES_REJECTED in known
    assert obs_names.COORD_FRAMES_REJECTED == "coord_frames_rejected"
    assert obs_names.GATEWAY_FRAMES_REJECTED == "gateway_frames_rejected"


def test_counters_share_registry():
    reg = Registry()
    a, b = Counters(registry=reg), Counters(registry=reg)
    a.inc("x")
    b.inc("x")
    assert a.get("x") == 2


# -- Prometheus rendering --------------------------------------------------


def test_render_prometheus_golden_text():
    reg = Registry()
    reg.counter("requests_total", help="total requests").inc(3)
    reg.gauge("depth").set(2.5)
    reg.observe("lat_seconds", 1.5, labels={"outcome": "hit"})
    text = render_prometheus(reg)
    lines = text.splitlines()
    assert "# HELP requests_total total requests" in lines
    assert "# TYPE requests_total counter" in lines
    assert "requests_total 3" in lines
    assert "depth 2.5" in lines
    i0 = lines.index("# TYPE lat_seconds histogram")
    bucket_lines = [l for l in lines if l.startswith("lat_seconds_bucket")]
    assert bucket_lines[-1] == 'lat_seconds_bucket{outcome="hit",le="+Inf"} 1'
    assert 'lat_seconds_count{outcome="hit"} 1' in lines
    assert lines.index(bucket_lines[0]) > i0
    assert text.endswith("\n")


def test_render_prometheus_validates_against_spec_parser():
    check = _load_check_metrics()
    reg = check._sample_registry()
    families = check.parse_exposition(render_prometheus(reg))
    check.check_invariants(families)
    assert families["latency_seconds"]["type"] == "histogram"


def test_spec_parser_rejects_malformed_text():
    check = _load_check_metrics()
    with pytest.raises(check.MetricsFormatError):
        check.parse_exposition("no_type_line 1\n")
    with pytest.raises(check.MetricsFormatError):
        check.parse_exposition("# TYPE x counter\nx 1")  # no trailing \n


# -- trace ring ------------------------------------------------------------


def _fake_clock():
    t = [0.0]

    def clock():
        t[0] += 1.0
        return t[0]

    return clock


def test_trace_ring_bounds_memory_and_counts_drops():
    log = TraceLog(capacity=4, clock=_fake_clock())
    for i in range(10):
        log.record("scheduled", (1, 0, i))
    assert len(log.events()) == 4
    assert log.recorded == 10
    assert log.dropped == 6


def test_trace_spans_join_lifecycle():
    log = TraceLog(clock=_fake_clock())
    key = (4, 1, 2)
    log.record("scheduled", key)                   # t=1
    log.record("granted", key, worker="w:1")       # t=2
    log.record("result_received", key, worker="w:1")  # t=3
    log.record("persisted", key)                   # t=4
    log.record("scheduled", (4, 0, 0))             # incomplete neighbour
    spans = {s["key"]: s for s in log.spans()}
    s = spans[key]
    assert s["complete"] is True
    assert s["worker"] == "w:1"
    assert s["queue_s"] == pytest.approx(1.0)
    assert s["compute_s"] == pytest.approx(1.0)
    assert s["persist_s"] == pytest.approx(1.0)
    assert s["total_s"] == pytest.approx(3.0)
    assert spans[(4, 0, 0)]["complete"] is False


def test_trace_spans_count_churn():
    log = TraceLog(clock=_fake_clock())
    key = (2, 0, 0)
    log.record("scheduled", key)
    log.record("granted", key, worker="w:1")
    log.record("lease_expired", key)
    log.record("requeued", key)
    log.record("granted", key, worker="w:2")
    log.record("result_received", key, worker="w:2")
    log.record("persisted", key)
    (s,) = log.spans()
    assert s["churn"] == 2
    assert s["worker"] == "w:2"  # the worker that actually delivered
    assert s["complete"] is True


def test_trace_worker_skew():
    log = TraceLog(clock=_fake_clock())
    # w:1 takes 1 s per tile (grant at t, receive at t+1); w:2's single
    # tile takes 3 s.
    for i in range(2):
        key = (4, 0, i)
        log.record("granted", key, worker="w:1")
        log.record("result_received", key, worker="w:1")
    key = (4, 1, 0)
    log.record("granted", key, worker="w:2")
    log.record("result_received", key, worker="w:2")
    skew = log.worker_skew()
    assert skew["workers"]["w:1"]["tiles"] == 2
    assert skew["workers"]["w:2"]["tiles"] == 1
    assert skew["skew"] >= 1.0
    assert TraceLog().worker_skew() == {"workers": {}, "skew": None}


# -- the HTTP exporter -----------------------------------------------------


def test_exporter_endpoints_on_embedded_coordinator(tmp_path):
    from distributedmandelbrot_tpu.core.workload import LevelSetting

    from harness import CoordinatorHarness

    with CoordinatorHarness(str(tmp_path), [LevelSetting(2, 16)]) as co:
        assert co.exporter_port
        base = f"http://127.0.0.1:{co.exporter_port}"
        assert urllib.request.urlopen(base + "/healthz",
                                      timeout=10).read() == b"ok\n"
        with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith(
                "text/plain; version=0.0.4")
            text = resp.read().decode()
        check = _load_check_metrics()
        families = check.parse_exposition(text)
        check.check_invariants(families)
        # The untouched frontier is fully grantable.
        assert families[obs_names.GAUGE_FRONTIER_DEPTH][
            "samples"][0][2] == 4.0
        varz = json.loads(urllib.request.urlopen(
            base + "/varz", timeout=10).read())
        assert varz["scheduler"] == {"frontier_depth": 4,
                                     "outstanding_leases": 0,
                                     "completed": 0, "total": 4}
        assert varz["trace"]["recorded"] == 0
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(base + "/nope", timeout=10)
        assert err.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                urllib.request.Request(base + "/metrics", data=b"x"),
                timeout=10)
        assert err.value.code == 405


def test_exporter_opt_out(tmp_path):
    from distributedmandelbrot_tpu.core.workload import LevelSetting

    from harness import CoordinatorHarness

    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, 16)],
                            exporter=False) as co:
        assert co.exporter_port is None
