"""Native C++ paths: build, parity with the Python/golden implementations."""

import struct

import numpy as np
import pytest

from distributedmandelbrot_tpu import native
from distributedmandelbrot_tpu.codecs.rle import RleCodec, find_runs
from distributedmandelbrot_tpu.core import TileSpec
from distributedmandelbrot_tpu.ops import reference as ref

pytestmark = pytest.mark.skipif(not native.native_supported(),
                                reason="native library unavailable")


def test_rle_encode_matches_python():
    rng = np.random.default_rng(3)
    for _ in range(5):
        runs = rng.integers(1, 40, size=rng.integers(1, 200))
        vals = rng.integers(0, 5, size=runs.size).astype(np.uint8)
        data = np.repeat(vals, runs)
        counts, values = find_runs(data)
        py_records = b"".join(struct.pack("<IB", c, v)
                              for c, v in zip(counts, values))
        assert native.rle_encode(data) == py_records


def test_rle_native_and_python_agree_bytewise_property():
    """Hypothesis-searched parity: the C++ and pure-Python RLE encoders
    must produce the SAME bytes and decode each other's output (a farm
    may mix hosts with and without the toolchain; stored payloads must
    interop).  Exercises the real shipped encoders on both sides."""
    pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    from distributedmandelbrot_tpu.codecs.rle import RleCodec

    arrays = st.one_of(
        st.binary(min_size=1, max_size=4096).map(
            lambda b: np.frombuffer(b, np.uint8)),
        st.lists(st.tuples(st.integers(1, 300), st.integers(0, 255)),
                 min_size=1, max_size=64).map(
            lambda runs: np.repeat(np.array([v for _, v in runs], np.uint8),
                                   np.array([n for n, _ in runs]))))

    codec = RleCodec()

    @settings(max_examples=200, deadline=None)
    @given(arrays)
    def prop(data):
        native_body = native.rle_encode(data)
        py_body = codec._encode_py(data)
        assert native_body == py_body
        np.testing.assert_array_equal(
            codec._decode_py(native_body, data.size), data)
        np.testing.assert_array_equal(
            native.rle_decode(py_body, data.size), data)

    prop()


def test_rle_decode_roundtrip_and_errors():
    data = np.repeat(np.array([7, 0, 255], np.uint8), [1000, 1, 65536])
    body = native.rle_encode(data)
    np.testing.assert_array_equal(native.rle_decode(body, data.size), data)
    with pytest.raises(ValueError):
        native.rle_decode(body[:-1], data.size)  # not a multiple of 5
    with pytest.raises(ValueError):
        native.rle_decode(struct.pack("<IB", 0, 1), 0)  # zero run
    with pytest.raises(ValueError):
        native.rle_decode(struct.pack("<IB", 9, 1), 4)  # overflow
    with pytest.raises(ValueError):
        native.rle_decode(struct.pack("<IB", 2, 1), 4)  # underfill


def test_codec_uses_native_transparently():
    """RleCodec must produce identical bytes whichever path is active."""
    codec = RleCodec()
    data = np.repeat(np.arange(16, dtype=np.uint8), 1000)
    body = codec.encode(data)
    counts, values = find_runs(data)
    assert len(body) == counts.size * 5
    np.testing.assert_array_equal(codec.decode(body, data.size), data)


@pytest.mark.parametrize("max_iter", [16, 256, 1000])
def test_escape_pixels_bit_identical_to_golden(max_iter):
    """The native kernel (built with -ffp-contract=off) is the fast
    bit-exact parity anchor: byte-for-byte equal to the numpy golden."""
    spec = TileSpec(-0.8, 0.1, 0.2, 0.2, width=96, height=96)
    cr, ci = spec.grid_2d()
    golden = ref.scale_counts_to_uint8(
        ref.escape_counts(cr, ci, max_iter), max_iter).ravel()
    got = native.escape_pixels(cr, ci, max_iter)
    np.testing.assert_array_equal(got, golden)
    # Multithreading must not change results.
    got4 = native.escape_pixels(cr, ci, max_iter, n_threads=4)
    np.testing.assert_array_equal(got4, golden)


def test_escape_counts_matches_golden():
    spec = TileSpec(-0.2, -0.1, 0.4, 0.4, width=64, height=64)
    cr, ci = spec.grid_2d()
    golden = ref.escape_counts(cr, ci, 300)
    np.testing.assert_array_equal(
        native.escape_counts(cr, ci, 300).reshape(golden.shape), golden)


def test_native_backend_end_to_end():
    from distributedmandelbrot_tpu.core import Workload
    from distributedmandelbrot_tpu.worker import NativeBackend

    backend = NativeBackend(definition=64)
    [pixels] = backend.compute_batch([Workload(4, 64, 1, 2)])
    spec = TileSpec.for_chunk(4, 1, 2, definition=64)
    cr, ci = spec.grid_2d()
    golden = ref.scale_counts_to_uint8(
        ref.escape_counts(cr, ci, 64), 64).ravel()
    np.testing.assert_array_equal(pixels, golden)


def test_scaling_wrap_parity_native():
    """The uint8 wrap at the escape ceiling must match the reference."""
    spec = TileSpec(0.25, 0.0, 0.02, 0.02, width=32, height=32)
    cr, ci = spec.grid_2d()
    golden = ref.scale_counts_to_uint8(ref.escape_counts(cr, ci, 1000), 1000)
    got = native.escape_pixels(cr, ci, 1000)
    np.testing.assert_array_equal(got, golden.ravel())
    clamped = native.escape_pixels(cr, ci, 1000, clamp=True)
    assert (clamped >= got).all()


def test_concurrent_first_load_is_thread_safe(monkeypatch, tmp_path):
    """Concurrent first use must never observe a half-done build attempt
    as 'unavailable' (regression: _tried was set before the build, so
    racing threads fell back to Python while one thread compiled)."""
    import threading

    from distributedmandelbrot_tpu.native import build

    # Fresh module state + an empty build dir so a real (cheap) build
    # races for real; restore globals afterwards via monkeypatch.
    monkeypatch.setattr(build, "_lib", None)
    monkeypatch.setattr(build, "_tried", False)
    monkeypatch.setattr(build, "_BUILD_DIR", str(tmp_path))
    monkeypatch.setattr(build, "_LIB_PATH",
                        str(tmp_path / "libdmtpu_native.so"))

    results = [None] * 8
    barrier = threading.Barrier(len(results))

    def probe(i: int) -> None:
        barrier.wait()
        results[i] = build.load()

    threads = [threading.Thread(target=probe, args=(i,))
               for i in range(len(results))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in threads), (
        "builder thread hung; results below would mislead and teardown "
        "would restore globals under a live loader")
    assert all(r is not None for r in results), (
        f"{sum(r is None for r in results)} of {len(results)} concurrent "
        "first loads saw the library as unavailable")
    assert len({id(r) for r in results}) == 1  # one shared CDLL


def test_fixed_escape_parity_with_python_bigint(monkeypatch):
    """The native limb kernel must match the Python-bigint loop exactly
    on every point class: escaping, in-set, boundary-delicate, Julia."""
    import random

    from distributedmandelbrot_tpu.native import bindings
    from distributedmandelbrot_tpu.ops import perturbation as P

    rng = random.Random(1234)
    for trial in range(40):
        bits = rng.choice([128, 192, 256, 384, 512])
        kind = trial % 4
        if kind == 0:
            cr, ci = rng.uniform(-2, 0.5), rng.uniform(-1.2, 1.2)
        elif kind == 1:  # Misiurewicz-adjacent boundary band
            cr = -0.7435 + rng.uniform(-1e-3, 1e-3)
            ci = 0.1318 + rng.uniform(-1e-3, 1e-3)
        elif kind == 2:  # deep interior (runs the full budget)
            cr, ci = rng.uniform(-0.2, 0.2), rng.uniform(-0.2, 0.2)
        else:  # wild, incl. immediate escapes
            cr, ci = rng.uniform(-2.5, 2.5), rng.uniform(-2.5, 2.5)
        mi = rng.choice([1, 2, 17, 300, 1500])
        za, zb = P._to_fixed(cr, bits), P._to_fixed(ci, bits)
        if kind == 3:  # julia: independent constant
            ca = P._to_fixed(rng.uniform(-1, 1), bits)
            cb = P._to_fixed(rng.uniform(-1, 1), bits)
        else:
            ca, cb = za, zb
        monkeypatch.setattr(P, "_native_fixed", lambda *a: False)
        want = P._escape_count_fixed(za, zb, mi, bits, ca=ca, cb=cb)
        monkeypatch.undo()
        got = bindings.fixed_escape(za, zb, ca, cb, mi, bits)
        assert got == want, (bits, cr, ci, mi, got, want)


def test_fixed_orbit_parity_with_python_bigint(monkeypatch):
    """Orbit arrays (float64 conversions incl. the round-to-nearest
    truncation and the post-escape huge-threshold extension) and the
    valid length must be bitwise identical to the Python loop."""
    import random

    from distributedmandelbrot_tpu.native import bindings
    from distributedmandelbrot_tpu.ops import perturbation as P

    rng = random.Random(99)
    cases = [("-0.743643887037158704752191506114774",
              "0.131825904205311970493132056385139", 256, 3000),
             ("-0.77568377", "0.13646737", 128, 2000),
             ("0.3", "0.5", 192, 500),  # escapes quickly -> extension
             ("0.0", "0.0", 512, 64)]   # superattracting fixed point
    for _ in range(8):
        cases.append((str(rng.uniform(-2, 0.5)), str(rng.uniform(-1, 1)),
                      rng.choice([128, 256]), rng.choice([1, 2, 400])))
    for cre, cim, bits, mi in cases:
        za, zb = P._to_fixed(cre, bits), P._to_fixed(cim, bits)
        monkeypatch.setattr(P, "_native_fixed", lambda *a: False)
        w_re, w_im, w_v = P._orbit_fixed.__wrapped__(za, zb, za, zb, mi,
                                                     bits)
        monkeypatch.undo()
        g_re, g_im, g_v = bindings.fixed_orbit(za, zb, za, zb, mi, bits,
                                               12)
        assert g_v == w_v, (cre, cim, bits, mi, g_v, w_v)
        np.testing.assert_array_equal(g_re, w_re)
        np.testing.assert_array_equal(g_im, w_im)


def test_fixed_kernels_reject_wild_inputs_to_python_path():
    """|c| >= 4 exceeds the native limb buffers' input bound; the
    wrapper must route such calls to the unbounded Python path, where
    they return correct counts instead of overflowing (regression:
    escape_counts_exact("2e19", "0", 100) raised OverflowError on the
    native path)."""
    from distributedmandelbrot_tpu.ops import perturbation as P

    assert P.escape_counts_exact("2e19", "0", 100) == 1
    assert P.escape_counts_exact("5.0", "0", 100) == 1
    # Near the bound, the native path still engages and agrees.
    assert P.escape_counts_exact("3.9", "0", 100) == 1


def test_fixed_escape_batch_parity_and_julia():
    """The threaded batch entry must agree pointwise with the scalar
    kernel in both families."""
    import random

    from distributedmandelbrot_tpu.native import bindings
    from distributedmandelbrot_tpu.ops import perturbation as P

    rng = random.Random(7)
    bits = 192
    pts = [(P._to_fixed(rng.uniform(-2, 0.6), bits),
            P._to_fixed(rng.uniform(-1.3, 1.3), bits)) for _ in range(32)]
    got = bindings.fixed_escape_batch(pts, 600, bits)
    want = [P._escape_count_fixed(a, b, 600, bits) for a, b in pts]
    assert list(got) == want
    jc = (P._to_fixed(-0.4, bits), P._to_fixed(0.6, bits))
    gotj = bindings.fixed_escape_batch(pts, 600, bits, julia_c=jc)
    wantj = [P._escape_count_fixed(a, b, 600, bits, ca=jc[0], cb=jc[1])
             for a, b in pts]
    assert list(gotj) == wantj
    # Multithreaded result identical to single-threaded.
    got4 = bindings.fixed_escape_batch(pts, 600, bits, n_threads=4)
    assert list(got4) == want
