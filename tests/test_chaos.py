"""Unit tests for the chaos scenario plumbing (chaos/runner.py).

These cover the pure, jax-free surface: the scenario catalogue, target
validation, the runner's expected-grid / per-shard ownership
precompute, and the report serialisation — plus one live
kill-and-restart farm (subprocess shards, SIGKILL, flight-recorder
dumps, postmortem reconstruction).  The full kill-schedule scenarios
(`dmtpu chaos`) are exercised by the CI smoke and the slow suite, not
here.
"""

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from distributedmandelbrot_tpu.chaos.runner import (ChaosReport,
                                                    ChaosRunner, KillEvent,
                                                    SCENARIOS, Scenario,
                                                    run_scenario)
from distributedmandelbrot_tpu.obs import events as obs_events
from distributedmandelbrot_tpu.obs import postmortem


def test_catalogue_is_sane():
    assert {"coord-kill", "coord-crashpoint", "worker-churn",
            "slow-persist", "storm"} <= set(SCENARIOS)
    for name, sc in SCENARIOS.items():
        assert sc.name == name
        assert sc.description
        assert sc.n_shards >= 1 and sc.n_workers >= 1
        assert sc.deadline > 0
        # Every scheduled kill and crashpoint must name a slot the farm
        # actually has — ChaosRunner validates this at construction, so
        # a bad catalogue entry fails here instead of mid-run.
        ChaosRunner(sc)


def test_scenario_replace_plumbing():
    sc = dataclasses.replace(SCENARIOS["coord-kill"], n_workers=1,
                             levels="3:2", parity_samples=1)
    assert sc.n_workers == 1
    assert SCENARIOS["coord-kill"].n_workers == 2  # catalogue untouched
    runner = ChaosRunner(sc)
    assert len(runner.workers) == 1
    assert len(runner.expected) == 9


def test_runner_precomputes_owned_partition():
    runner = ChaosRunner(Scenario(name="t", levels="4:2", n_shards=3))
    assert runner.expected == {(4, i, j)
                               for i in range(4) for j in range(4)}
    # owned_expected is a partition of the grid by ring owner.
    assert set().union(*runner.owned_expected) == runner.expected
    total = sum(len(s) for s in runner.owned_expected)
    assert total == len(runner.expected)
    for shard, keys in enumerate(runner.owned_expected):
        assert all(runner.ring.owner_of(k) == shard for k in keys)


def test_runner_rejects_bad_targets():
    with pytest.raises(ValueError):
        ChaosRunner(Scenario(name="t", n_shards=2,
                             kills=(KillEvent(1.0, "coord:2"),)))
    with pytest.raises(ValueError):
        ChaosRunner(Scenario(name="t",
                             kills=(KillEvent(1.0, "gateway:0"),)))
    with pytest.raises(ValueError):
        ChaosRunner(Scenario(name="t",
                             kills=(KillEvent(1.0, "coord:x"),)))
    with pytest.raises(ValueError):
        # Crashpoints ride DMTPU_CRASHPOINTS in the coordinator env;
        # a worker target would silently never fire.
        ChaosRunner(Scenario(name="t",
                             crashpoints={"worker:0": "x:1"}))


def test_report_to_json_round_trips():
    report = ChaosReport(
        scenario="coord-kill", ok=False, duration_s=12.3,
        expected_tiles=9, tiles_on_disk=8, duplicate_entries=0,
        misowned_entries=0, parity_checked=2, parity_failures=0,
        kills=1, restarts=1, restart_to_first_grant_s=[0.42],
        failures=["1 tiles never completed (first: (3, 0, 0))"])
    doc = json.loads(report.to_json())
    assert doc["scenario"] == "coord-kill"
    assert doc["ok"] is False
    assert doc["restart_to_first_grant_s"] == [0.42]
    assert doc["failures"]


def test_run_scenario_rejects_unknown_name():
    with pytest.raises(ValueError, match="unknown scenario"):
        run_scenario("does-not-exist")


def test_report_carries_postmortem_summary():
    report = ChaosReport(
        scenario="coord-kill", ok=False, duration_s=1.0,
        expected_tiles=9, tiles_on_disk=8, duplicate_entries=0,
        misowned_entries=0, parity_checked=0, parity_failures=0,
        kills=1, restarts=1, failures=["x"],
        postmortem={"processes": [], "anomalies": []})
    doc = json.loads(report.to_json())
    assert doc["postmortem"]["anomalies"] == []
    # ok reports stay lean: the field defaults empty.
    assert ChaosReport(scenario="s", ok=True, duration_s=0.0,
                       expected_tiles=0, tiles_on_disk=0,
                       duplicate_entries=0, misowned_entries=0,
                       parity_checked=0, parity_failures=0,
                       kills=0, restarts=0).postmortem == {}


# -- live kill-and-restart farm ---------------------------------------------

_DRIVER = "distributedmandelbrot_tpu.chaos.driver"


def _farm_env(flight_dir: str) -> dict:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["DMTPU_FLIGHT_DIR"] = flight_dir
    env["DMTPU_FLIGHT_PERIOD"] = "0.1"  # autoflush = the SIGKILL survivor
    return env


def _spawn_shard(tmp, flight_dir, tag, shard, n_shards):
    port_file = os.path.join(tmp, f"ports-{tag}.json")
    proc = subprocess.Popen(
        [sys.executable, "-m", _DRIVER, "shard",
         os.path.join(tmp, "farm"), port_file, "8:16",
         str(shard), str(n_shards),
         "--lease-timeout", "0.05", "--sweep-period", "0.02",
         "--checkpoint-period", "0"],
        env=_farm_env(flight_dir), stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)
    return proc, port_file


def _read_ports(proc, port_file, deadline_s=60.0):
    deadline = time.monotonic() + deadline_s
    while not os.path.exists(port_file):
        if proc.poll() is not None:
            raise RuntimeError(
                f"shard died during startup (exit {proc.returncode})")
        if time.monotonic() > deadline:
            proc.kill()
            raise RuntimeError("shard never wrote its port file")
        time.sleep(0.05)
    with open(port_file, "r", encoding="utf-8") as f:
        return json.load(f)


def _save_ring(tmp, infos):
    from distributedmandelbrot_tpu.control.ring import HashRing, ShardInfo
    ring_path = os.path.join(tmp, "ring.json")
    HashRing([ShardInfo("127.0.0.1",
                        distributer_port=i["distributer"],
                        dataserver_port=i["dataserver"],
                        exporter_port=i["exporter"])
              for i in infos], version=1).save(ring_path)
    return ring_path


def test_kill_and_restart_postmortem_reconstructs_the_fleet(tmp_path):
    """SIGKILL a shard under grant storm, restart it, and assemble the
    flight dumps: the killed incarnation's black box survives via
    autoflush, the restarted incarnation's grants land causally after
    the kill, and the survivors dump cleanly at SIGTERM."""
    tmp = str(tmp_path)
    flight_dir = os.path.join(tmp, "flight")
    os.makedirs(flight_dir)
    procs = []
    drain = None
    try:
        shard0, pf0 = _spawn_shard(tmp, flight_dir, "s0", 0, 2)
        shard1, pf1 = _spawn_shard(tmp, flight_dir, "s1", 1, 2)
        procs += [shard0, shard1]
        infos = [_read_ports(shard0, pf0), _read_ports(shard1, pf1)]
        ring_path = _save_ring(tmp, infos)
        drain = subprocess.Popen(
            [sys.executable, "-m", _DRIVER, "drain", ring_path,
             "--duration", "4.5", "--batch", "16",
             "--out", os.path.join(tmp, "drain.json")],
            env=_farm_env(flight_dir), stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        # Let the storm run long enough for several autoflush periods,
        # then SIGKILL shard 0 mid-grant: no exit hook runs, so its dump
        # is whatever the last autoflush wrote.
        time.sleep(1.5)
        t_kill_wall = time.time()
        shard0.kill()
        shard0.wait()
        killed_pid = infos[0]["pid"]
        # Restart shard 0 (fresh pid, same shard index + data dir) and
        # re-publish the ring so the drain client re-dials it.
        shard0b, pf0b = _spawn_shard(tmp, flight_dir, "s0b", 0, 2)
        procs.append(shard0b)
        infos[0] = _read_ports(shard0b, pf0b)
        _save_ring(tmp, infos)
        drain.wait(timeout=90.0)
        with open(os.path.join(tmp, "drain.json"), encoding="utf-8") as f:
            assert json.load(f)["grants"] > 0
        # SIGTERM is the graceful path: coordinator.stop() then exit,
        # which rewrites each survivor's dump with reason=atexit.
        for proc in (shard0b, shard1):
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=60.0)
    finally:
        if drain is not None and drain.poll() is None:
            drain.kill()
            drain.wait()
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    pm = postmortem.assemble(flight_dir)
    by_pid = {d.header.get("pid"): d for d in pm.dumps}
    killed = by_pid[killed_pid]
    assert killed.role == "shard-0"
    assert killed.header["reason"] == "autoflush"  # SIGKILL: no exit hook
    survivors = [d for d in pm.dumps if d.header.get("pid") != killed_pid]
    assert {d.role for d in survivors} == {"shard-0", "shard-1"}
    assert all(d.header["reason"] == "atexit" for d in survivors)
    # The killed incarnation granted leases, and the restarted
    # incarnation's grants all land after the kill on the merged clock.
    killed_grants = [e for e in pm.timeline if e["proc"] == killed.proc
                     and e["name"] == obs_events.SCHED_GRANT]
    assert killed_grants
    restarted = next(d for d in survivors if d.role == "shard-0")
    restarted_grants = [e for e in pm.timeline
                        if e["proc"] == restarted.proc
                        and e["name"] == obs_events.SCHED_GRANT]
    assert restarted_grants
    assert killed_grants[-1]["t"] < t_kill_wall < restarted_grants[0]["t"]
    assert pm.summary()["events"] == len(pm.timeline)
