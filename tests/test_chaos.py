"""Unit tests for the chaos scenario plumbing (chaos/runner.py).

These cover the pure, jax-free surface: the scenario catalogue, target
validation, the runner's expected-grid / per-shard ownership
precompute, and the report serialisation.  The live kill-schedule runs
(`dmtpu chaos`) are exercised by the CI smoke and the slow suite, not
here.
"""

import dataclasses
import json

import pytest

from distributedmandelbrot_tpu.chaos.runner import (ChaosReport,
                                                    ChaosRunner, KillEvent,
                                                    SCENARIOS, Scenario,
                                                    run_scenario)


def test_catalogue_is_sane():
    assert {"coord-kill", "coord-crashpoint", "worker-churn",
            "slow-persist", "storm"} <= set(SCENARIOS)
    for name, sc in SCENARIOS.items():
        assert sc.name == name
        assert sc.description
        assert sc.n_shards >= 1 and sc.n_workers >= 1
        assert sc.deadline > 0
        # Every scheduled kill and crashpoint must name a slot the farm
        # actually has — ChaosRunner validates this at construction, so
        # a bad catalogue entry fails here instead of mid-run.
        ChaosRunner(sc)


def test_scenario_replace_plumbing():
    sc = dataclasses.replace(SCENARIOS["coord-kill"], n_workers=1,
                             levels="3:2", parity_samples=1)
    assert sc.n_workers == 1
    assert SCENARIOS["coord-kill"].n_workers == 2  # catalogue untouched
    runner = ChaosRunner(sc)
    assert len(runner.workers) == 1
    assert len(runner.expected) == 9


def test_runner_precomputes_owned_partition():
    runner = ChaosRunner(Scenario(name="t", levels="4:2", n_shards=3))
    assert runner.expected == {(4, i, j)
                               for i in range(4) for j in range(4)}
    # owned_expected is a partition of the grid by ring owner.
    assert set().union(*runner.owned_expected) == runner.expected
    total = sum(len(s) for s in runner.owned_expected)
    assert total == len(runner.expected)
    for shard, keys in enumerate(runner.owned_expected):
        assert all(runner.ring.owner_of(k) == shard for k in keys)


def test_runner_rejects_bad_targets():
    with pytest.raises(ValueError):
        ChaosRunner(Scenario(name="t", n_shards=2,
                             kills=(KillEvent(1.0, "coord:2"),)))
    with pytest.raises(ValueError):
        ChaosRunner(Scenario(name="t",
                             kills=(KillEvent(1.0, "gateway:0"),)))
    with pytest.raises(ValueError):
        ChaosRunner(Scenario(name="t",
                             kills=(KillEvent(1.0, "coord:x"),)))
    with pytest.raises(ValueError):
        # Crashpoints ride DMTPU_CRASHPOINTS in the coordinator env;
        # a worker target would silently never fire.
        ChaosRunner(Scenario(name="t",
                             crashpoints={"worker:0": "x:1"}))


def test_report_to_json_round_trips():
    report = ChaosReport(
        scenario="coord-kill", ok=False, duration_s=12.3,
        expected_tiles=9, tiles_on_disk=8, duplicate_entries=0,
        misowned_entries=0, parity_checked=2, parity_failures=0,
        kills=1, restarts=1, restart_to_first_grant_s=[0.42],
        failures=["1 tiles never completed (first: (3, 0, 0))"])
    doc = json.loads(report.to_json())
    assert doc["scenario"] == "coord-kill"
    assert doc["ok"] is False
    assert doc["restart_to_first_grant_s"] == [0.42]
    assert doc["failures"]


def test_run_scenario_rejects_unknown_name():
    with pytest.raises(ValueError, match="unknown scenario"):
        run_scenario("does-not-exist")
