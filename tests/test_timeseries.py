"""Timeseries sampler: ring-buffer history, derived rate/percentile
series, the /timeseries JSON documents, and the exporter endpoint."""

import json
import urllib.error
import urllib.request

import pytest

from distributedmandelbrot_tpu.coordinator.clock import ManualClock
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.metrics import Registry
from distributedmandelbrot_tpu.obs.timeseries import (TimeseriesSampler,
                                                      family_of)


def make_sampler(period=1.0, window=60.0):
    reg = Registry()
    clk = ManualClock()
    sampler = TimeseriesSampler(reg, period=period, window=window,
                                clock=clk.now)
    return reg, clk, sampler


# -- construction and bounds -----------------------------------------------


def test_sampler_rejects_bad_periods():
    reg = Registry()
    with pytest.raises(ValueError, match="period"):
        TimeseriesSampler(reg, period=0.0)
    with pytest.raises(ValueError, match="window"):
        TimeseriesSampler(reg, period=10.0, window=5.0)


def test_sampler_capacity_bounds_memory():
    reg, clk, sampler = make_sampler(period=1.0, window=10.0)
    assert sampler.capacity == 12  # window/period + 2
    for _ in range(100):
        clk.advance(1.0)
        sampler.sample()
    # The deque, not a policy loop, enforces the bound.
    assert len(sampler) == sampler.capacity


def test_family_of():
    assert family_of("queries{outcome=tier1_hit}") == "queries"
    assert family_of("plain") == "plain"


# -- counters: points and rates --------------------------------------------


def test_counter_points_and_rates_on_manual_clock():
    reg, clk, sampler = make_sampler()
    c = reg.counter("grants")
    for step in (10, 30, 30):
        c.inc(step)
        clk.advance(2.0)
        sampler.sample()
    pts = sampler.counter_points("grants")
    assert [v for _, v in pts] == [10, 40, 70]
    rates = sampler.rates_from_points(pts)
    assert [r for _, r in rates] == [pytest.approx(15.0),
                                     pytest.approx(15.0)]
    # Window rate is first-vs-last inside the trailing window.
    assert sampler.rate("grants", window=60.0) == pytest.approx(15.0)
    # A window too narrow to hold 2 points yields 0, not an exception.
    assert sampler.rate("grants", window=0.5) == 0.0


def test_counter_family_sums_labeled_children():
    reg, clk, sampler = make_sampler()
    reg.inc("served", 3, labels={"outcome": "tier1_hit"})
    reg.inc("served", 4, labels={"outcome": "computed"})
    clk.advance(1.0)
    sampler.sample()
    assert sampler.counter_points("served") == [(1.0, 7)]
    assert sampler.counter_points("served{outcome=computed}") == [(1.0, 4)]


def test_rates_clamp_counter_resets_to_zero():
    # A restart resets counters; the plot must not show a negative spike.
    pts = [(0.0, 100.0), (1.0, 5.0), (2.0, 10.0)]
    rates = TimeseriesSampler.rates_from_points(pts)
    assert rates == [(1.0, 0.0), (2.0, pytest.approx(5.0))]


def test_window_trims_old_samples():
    reg, clk, sampler = make_sampler()
    c = reg.counter("x")
    for _ in range(5):
        c.inc()
        clk.advance(10.0)
        sampler.sample()
    assert len(sampler.counter_points("x")) == 5
    assert len(sampler.counter_points("x", window=25.0)) == 3


# -- gauges and histograms -------------------------------------------------


def test_gauge_points():
    reg, clk, sampler = make_sampler()
    g = reg.gauge("depth")
    for v in (1.0, 5.0, 2.0):
        g.set(v)
        clk.advance(1.0)
        sampler.sample()
    assert [v for _, v in sampler.gauge_points("depth")] == [1.0, 5.0, 2.0]


def test_hist_points_merge_family_children():
    reg, clk, sampler = make_sampler()
    reg.histogram("lat", buckets=[1.0, 2.0])  # binds the family bounds
    reg.observe("lat", 0.5, labels={"outcome": "a"})
    reg.observe("lat", 1.5, labels={"outcome": "b"})
    clk.advance(1.0)
    sampler.sample()
    [(ts, counts, total, count)] = sampler.hist_points("lat")
    assert ts == 1.0
    assert counts == [1, 1, 0]  # merged across children + overflow
    assert count == 2
    assert total == pytest.approx(2.0)
    assert sampler.bounds_for("lat") == (1.0, 2.0)


def test_percentile_series_uses_interval_deltas():
    reg, clk, sampler = make_sampler()
    h = reg.histogram("lat", buckets=[1.0, 2.0, 4.0])
    h.observe(0.5)  # cumulative history starts fast
    clk.advance(1.0)
    sampler.sample()
    for _ in range(8):
        h.observe(3.0)  # the interval turns slow
    clk.advance(1.0)
    sampler.sample()
    series = sampler.percentile_series("lat", 50.0)
    # The interval p50 reflects only the 8 slow observations, unpolluted
    # by the fast cumulative past.
    assert len(series) == 1
    assert series[0][1] == pytest.approx(3.0)
    # An idle interval carries the cumulative percentile forward: a
    # quiet gateway plots its steady latency, not zeros.
    clk.advance(1.0)
    sampler.sample()
    idle = sampler.percentile_series("lat", 50.0)
    assert len(idle) == 2
    assert idle[1][1] > 0.0


def test_window_percentile_deltas_first_vs_last():
    reg, clk, sampler = make_sampler()
    h = reg.histogram("lat", buckets=[1.0, 2.0, 4.0])
    for _ in range(10):
        h.observe(0.5)
    clk.advance(1.0)
    sampler.sample()
    for _ in range(10):
        h.observe(3.0)
    clk.advance(1.0)
    sampler.sample()
    # Whole history: 50/50 fast/slow.
    whole = sampler.window_percentile("lat", 99.0)
    assert whole == pytest.approx(4.0, rel=0.1)
    # Unknown family: 0.0, not a crash.
    assert sampler.window_percentile("missing", 50.0) == 0.0


# -- /timeseries documents -------------------------------------------------


def test_to_json_catalogue_and_series():
    reg, clk, sampler = make_sampler()
    c = reg.counter("grants")
    reg.histogram("lat", buckets=[1.0, 2.0]).observe(0.5)
    for _ in range(3):
        c.inc(10)
        clk.advance(2.0)
        sampler.sample()
    cat = sampler.to_json()
    assert "grants" in cat["series"]
    assert "lat" in cat["series"]
    assert cat["samples"] == 3
    assert cat["period_s"] == 1.0

    doc = sampler.to_json("grants")
    assert doc["kind"] == "counter"
    assert len(doc["points"]) == 3
    assert len(doc["rates"]) == 2
    assert doc["window_rate"] == pytest.approx(5.0)

    hist = sampler.to_json("lat")
    assert hist["kind"] == "histogram"
    assert [n for _, n in hist["counts"]] == [1, 1, 1]
    assert "p50" in hist["percentiles"]
    assert "p99" in hist["percentiles"]
    assert hist["window_p50"] == pytest.approx(0.5, abs=0.5)

    unknown = sampler.to_json("nope")
    assert "unknown series" in unknown["error"]
    assert "grants" in unknown["series"]


def test_sampler_self_instrumentation():
    reg, clk, sampler = make_sampler()
    reg.counter("x").inc()
    reg.gauge("g").set(1.0)
    clk.advance(1.0)
    sampler.sample()
    assert reg.counter_value(obs_names.TS_SAMPLES) == 1
    # x + g + the sampler's own ts_samples from the first cut are not
    # yet visible to itself; the series gauge counts the cut it took.
    assert reg.gauge(obs_names.GAUGE_TS_SERIES).read() >= 2


# -- the exporter endpoint -------------------------------------------------


def test_timeseries_endpoint_on_embedded_coordinator(tmp_path):
    from distributedmandelbrot_tpu.core.workload import LevelSetting

    from harness import CoordinatorHarness

    with CoordinatorHarness(str(tmp_path), [LevelSetting(2, 16)]) as co:
        sampler = co.coordinator.sampler
        assert sampler is not None
        # Drive the sampler by hand instead of waiting out real periods;
        # sample() is thread-safe by contract.
        sampler.sample()
        co.coordinator.registry.inc(obs_names.COORD_WORKLOADS_GRANTED, 5)
        sampler.sample()
        base = f"http://127.0.0.1:{co.exporter_port}"
        cat = json.loads(urllib.request.urlopen(
            base + "/timeseries", timeout=10).read())
        assert obs_names.GAUGE_FRONTIER_DEPTH in cat["series"]
        doc = json.loads(urllib.request.urlopen(
            base + "/timeseries?name="
            + obs_names.COORD_WORKLOADS_GRANTED, timeout=10).read())
        assert doc["kind"] == "counter"
        assert doc["points"][-1][1] == 5
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                base + "/timeseries?name=definitely_not_a_series",
                timeout=10)
        assert err.value.code == 404
        assert "error" in json.loads(err.value.read())
        # Garbage window falls back to whole history, not a 500.
        ok = json.loads(urllib.request.urlopen(
            base + "/timeseries?name="
            + obs_names.COORD_WORKLOADS_GRANTED + "&window=banana",
            timeout=10).read())
        assert ok["kind"] == "counter"
