"""Kernel parity tests: JAX escape-time vs the numpy golden reference."""

import numpy as np
import pytest

from distributedmandelbrot_tpu.core import TileSpec
from distributedmandelbrot_tpu.ops import (compute_tile, escape_counts,
                                           scale_counts_to_uint8)
from distributedmandelbrot_tpu.ops import reference as ref


def grids(spec):
    return spec.grid_2d()


# Small but representative views: full set, boundary detail, all-escape, all-in.
VIEWS = [
    TileSpec(-2.0, -2.0, 4.0, 4.0, width=64, height=64),          # level-1 chunk
    TileSpec(-0.8, 0.1, 0.2, 0.2, width=64, height=64),           # boundary
    TileSpec(1.5, 1.5, 0.5, 0.5, width=32, height=32),            # all escape fast
    TileSpec(-0.2, -0.1, 0.2, 0.2, width=32, height=32),          # interior (in-set)
]


@pytest.mark.parametrize("spec", VIEWS)
@pytest.mark.parametrize("max_iter", [2, 17, 256, 1000])
def test_f64_counts_near_identical_to_golden(spec, max_iter):
    """f64 JAX vs golden: XLA FMA contraction can shift O(1) chaotic-boundary
    pixels per tile (see ops/escape_time.py docstring); everything else must
    be bit-identical.  Bit-exact parity is anchored by the host paths."""
    cr, ci = grids(spec)
    golden = ref.escape_counts(cr, ci, max_iter)
    got = np.asarray(escape_counts(cr, ci, max_iter=max_iter))
    mismatched = got != golden
    assert mismatched.mean() <= 5e-4, (
        f"f64 path diverges on {mismatched.mean():.2%} of pixels")
    if mismatched.any():
        # Divergence is only credible deep in the iteration tail (chaotic
        # boundary); early escapes must agree exactly.
        assert golden[mismatched].min() >= 50


@pytest.mark.parametrize("segment", [1, 7, 32, 1024])
def test_segment_size_does_not_change_result(segment):
    """Early-exit segmentation is a pure scheduling choice — results must be
    bit-identical across segment sizes."""
    spec = VIEWS[1]
    cr, ci = grids(spec)
    base = np.asarray(escape_counts(cr, ci, max_iter=300, segment=300))
    got = np.asarray(escape_counts(cr, ci, max_iter=300, segment=segment))
    np.testing.assert_array_equal(got, base)


def test_max_iter_one_yields_all_zero():
    cr, ci = grids(VIEWS[0])
    got = np.asarray(escape_counts(cr, ci, max_iter=1))
    assert (got == 0).all()


def test_counts_range():
    cr, ci = grids(VIEWS[1])
    got = np.asarray(escape_counts(cr, ci, max_iter=100))
    # Max representable escape iteration is max_iter - 1 (loop range(1, mrd)).
    assert got.max() <= 99 and got.min() >= 0


@pytest.mark.parametrize("max_iter", [256, 1000, 50000])
def test_uint8_scaling_parity_including_wrap(max_iter):
    counts = np.arange(0, max_iter, max(1, max_iter // 3000), dtype=np.int32)
    golden = ref.scale_counts_to_uint8(counts, max_iter)
    got = np.asarray(scale_counts_to_uint8(counts, max_iter=max_iter))
    np.testing.assert_array_equal(got, golden)
    if max_iter > 256:
        # The reference wrap: a pixel escaping near the ceiling reads 0.
        near_ceiling = np.array([max_iter - 1], dtype=np.int32)
        assert ref.scale_counts_to_uint8(near_ceiling, max_iter)[0] == 0
        assert np.asarray(
            scale_counts_to_uint8(near_ceiling, max_iter=max_iter))[0] == 0


def test_uint8_scaling_huge_max_iter_widens_beyond_int32():
    """counts*256 overflows int32 for max_iter > 2^23; the kernel must widen
    and still match the float64 golden path."""
    max_iter = 10_000_000
    counts = np.array([0, 1, 9_000_000, max_iter - 1], dtype=np.int32)
    golden = ref.scale_counts_to_uint8(counts, max_iter)
    got = np.asarray(scale_counts_to_uint8(counts, max_iter=max_iter))
    np.testing.assert_array_equal(got, golden)


def test_uint8_scaling_clamp_mode():
    counts = np.array([999], dtype=np.int32)
    assert np.asarray(
        scale_counts_to_uint8(counts, max_iter=1000, clamp=True))[0] == 255


def test_compute_tile_f64_matches_golden_end_to_end():
    spec = TileSpec.for_chunk(4, 1, 2, definition=64)
    cr, ci = grids(spec)
    golden = ref.scale_counts_to_uint8(ref.escape_counts(cr, ci, 256), 256)
    got = compute_tile(spec, 256, dtype=np.float64)
    mismatch = (got != golden.ravel()).mean()
    assert mismatch <= 5e-4, f"{mismatch:.2%} of pixels diverge"


def test_compute_tile_f32_close_to_golden():
    """The fast path may differ only at boundary pixels (last-ulp effects)."""
    spec = TileSpec(-0.8, 0.1, 0.2, 0.2, width=128, height=128)
    cr, ci = grids(spec)
    golden = ref.scale_counts_to_uint8(ref.escape_counts(cr, ci, 256), 256)
    got = compute_tile(spec, 256, dtype=np.float32)
    mismatch = (got != golden.ravel()).mean()
    assert mismatch < 0.02, f"f32 path diverges on {mismatch:.1%} of pixels"


@pytest.mark.parametrize("seed", range(4))
def test_random_views_f64_parity(seed):
    """Seeded random views (center, span, budget) vs the golden — catches
    regressions outside the hand-picked VIEWS, including interior/cycle
    shortcut interactions anywhere in the plane."""
    rng = np.random.default_rng(1234 + seed)
    cx, cy = rng.uniform(-2.0, 2.0, size=2)
    span = float(10.0 ** rng.uniform(-3, 0.6))
    max_iter = int(rng.integers(50, 500))
    spec = TileSpec(cx - span / 2, cy - span / 2, span, span,
                    width=64, height=64)
    cr, ci = grids(spec)
    golden = ref.escape_counts(cr, ci, max_iter)
    # cycle_check forced on: the auto policy only enables the probe at
    # budgets >= 4096, and these random budgets must still exercise it.
    got = np.asarray(escape_counts(cr, ci, max_iter=max_iter,
                                   cycle_check=True))
    mism = (got != golden).mean()
    assert mism <= 5e-4, (
        f"seed {seed} (c={cx:.4f},{cy:.4f} span={span:.3g} "
        f"mi={max_iter}): {mism:.2%} mismatch")


# ---------------------------------------------------------------------------
# Closed-form interior shortcut (main cardioid + period-2 bulb).

# Views chosen to exercise the shortcut's three regimes: deep inside the
# curves, straddling their boundaries, and not touching them at all.
INTERIOR_VIEWS = [
    TileSpec(-0.6, -0.4, 0.8, 0.8, width=96, height=96),    # cardioid bulk
    TileSpec(-1.2, -0.2, 0.4, 0.4, width=96, height=96),    # period-2 bulb
    TileSpec(-0.748, 0.09, 0.02, 0.02, width=96, height=96),  # seahorse straddle
    TileSpec(-2.0, -2.0, 4.0, 4.0, width=96, height=96),    # full view
]


@pytest.mark.parametrize("spec", INTERIOR_VIEWS)
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_interior_check_is_output_identical(spec, dtype):
    """The cardioid/bulb shortcut is a pure work optimization: counts with
    the check on must equal counts with it off, bit for bit."""
    cr, ci = grids(spec)
    import jax.numpy as jnp
    cr = jnp.asarray(cr, dtype)
    ci = jnp.asarray(ci, dtype)
    on = np.asarray(escape_counts(cr, ci, max_iter=600, interior_check=True))
    off = np.asarray(escape_counts(cr, ci, max_iter=600,
                                   interior_check=False))
    np.testing.assert_array_equal(on, off)


def test_interior_mask_pixels_never_escape_in_golden():
    """Every pixel the closed-form test claims is interior must be a pixel
    the golden reference finds never escapes (the converse need not hold:
    higher-period components are not covered by the test)."""
    from distributedmandelbrot_tpu.ops.escape_time import mandelbrot_interior
    spec = TileSpec(-2.0, -1.25, 2.5, 2.5, width=160, height=160)
    cr, ci = grids(spec)
    golden = ref.escape_counts(cr, ci, 2000)
    mask = np.asarray(mandelbrot_interior(cr.astype(np.float32),
                                          ci.astype(np.float32)))
    assert mask.any()  # the view crosses both curves
    assert (golden[mask] == 0).all(), (
        f"{(golden[mask] != 0).sum()} shortcut pixels escaped in the golden")


def test_cycle_check_is_output_identical():
    """The Brent periodicity probe is a pure work optimization: an orbit
    bitwise-equal to its snapshot repeats forever, so saturating it must
    not change a single count."""
    import jax.numpy as jnp
    for spec in (TileSpec(-0.2, 0.7, 0.15, 0.15, width=96, height=96),
                 INTERIOR_VIEWS[2]):
        cr, ci = grids(spec)
        cr = jnp.asarray(cr, jnp.float32)
        ci = jnp.asarray(ci, jnp.float32)
        base = np.asarray(escape_counts(cr, ci, max_iter=500,
                                        interior_check=False,
                                        cycle_check=False))
        cyc = np.asarray(escape_counts(cr, ci, max_iter=500,
                                       interior_check=False,
                                       cycle_check=True))
        np.testing.assert_array_equal(base, cyc)


def test_cycle_check_julia_is_output_identical():
    from distributedmandelbrot_tpu.ops.escape_time import escape_counts_julia
    import jax.numpy as jnp
    spec = TileSpec(-1.5, -1.5, 3.0, 3.0, width=96, height=96)
    zr, zi = grids(spec)
    zr = jnp.asarray(zr, jnp.float32)
    zi = jnp.asarray(zi, jnp.float32)
    c = -0.4 + 0.1j  # attracting fixed point: connected Julia interior
    base = np.asarray(escape_counts_julia(zr, zi, c, max_iter=500,
                                          cycle_check=False))
    cyc = np.asarray(escape_counts_julia(zr, zi, c, max_iter=500,
                                         cycle_check=True))
    np.testing.assert_array_equal(base, cyc)
    assert (cyc == 0).sum() > 0  # the view does contain in-set pixels


def test_cycle_check_actually_retires_lanes():
    """Effectiveness, observed through work: on a tile deep inside the
    period-3 bulb (every orbit collapses to an exact f32 3-cycle within a
    few hundred iterations; the cardioid/bulb closed forms do NOT cover
    it), the probe must early-exit the segmented loop instead of burning
    the full budget.  Wall-clock with a generous margin — probe-on skips
    >97% of the iterations, so even noisy CI clears 2x."""
    import time
    import jax.numpy as jnp
    spec = TileSpec(-0.13, 0.74, 0.01, 0.01, width=64, height=64)
    cr, ci = grids(spec)
    cr = jnp.asarray(cr, jnp.float32)
    ci = jnp.asarray(ci, jnp.float32)
    golden = ref.escape_counts(np.asarray(cr, np.float64),
                               np.asarray(ci, np.float64), 2000)
    assert (golden == 0).all(), "view must be entirely in-set"

    def timed(**kw):
        out = np.asarray(escape_counts(cr, ci, max_iter=30000,
                                       interior_check=False, **kw))
        assert (out == 0).all()
        best = float("inf")  # min-of-3 compiled runs: noise-robust
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(escape_counts(cr, ci, max_iter=30000,
                                     interior_check=False, **kw))
            best = min(best, time.perf_counter() - t0)
        return best

    t_off = timed(cycle_check=False)
    t_on = timed(cycle_check=True)
    assert t_on < t_off / 2, (
        f"probe-on {t_on:.3f}s not clearly faster than probe-off "
        f"{t_off:.3f}s — cycle detection is not retiring lanes")


def test_cycle_check_smooth_is_output_identical():
    from distributedmandelbrot_tpu.ops.escape_time import escape_smooth
    import jax.numpy as jnp
    spec = TileSpec(-0.2, 0.7, 0.15, 0.15, width=96, height=96)
    cr, ci = grids(spec)
    cr = jnp.asarray(cr, jnp.float32)
    ci = jnp.asarray(ci, jnp.float32)
    base = np.asarray(escape_smooth(cr, ci, max_iter=500,
                                    interior_check=False, cycle_check=False))
    cyc = np.asarray(escape_smooth(cr, ci, max_iter=500,
                                   interior_check=False, cycle_check=True))
    np.testing.assert_array_equal(base, cyc)


def test_interior_smooth_is_output_identical():
    from distributedmandelbrot_tpu.ops.escape_time import escape_smooth
    import jax.numpy as jnp
    spec = INTERIOR_VIEWS[2]
    cr, ci = grids(spec)
    cr = jnp.asarray(cr, jnp.float32)
    ci = jnp.asarray(ci, jnp.float32)
    on = np.asarray(escape_smooth(cr, ci, max_iter=600,
                                  interior_check=True))
    off = np.asarray(escape_smooth(cr, ci, max_iter=600,
                                   interior_check=False))
    np.testing.assert_array_equal(on, off)


# ---------------------------------------------------------------------------
# Smooth (continuous) coloring — the quality/deep-zoom extension.

@pytest.mark.parametrize("spec", VIEWS)
@pytest.mark.parametrize("max_iter", [2, 17, 256])
def test_smooth_classification_matches_integer_path(spec, max_iter):
    """nu == 0 iff escape_counts == 0 — including pixels whose radius-2
    escape lands in the last iterations of the budget.  Tolerance matches
    the integer-path golden tests: FMA contraction may shift O(1)
    chaotic-boundary pixels across the budget edge (module docstring)."""
    from distributedmandelbrot_tpu.ops import escape_smooth
    cr, ci = grids(spec)
    nu = np.asarray(escape_smooth(cr, ci, max_iter=max_iter))
    counts = np.asarray(ref.escape_counts(cr, ci, max_iter))
    mismatch = ((nu == 0.0) != (counts == 0)).mean()
    assert mismatch <= 5e-4, f"{mismatch:.2%} classification divergence"
    assert (nu[nu != 0] > 0.0).all()
    assert np.isfinite(nu).all()


def test_smooth_tracks_integer_counts():
    """nu and the radius-2 escape count agree to within the bailout shift:
    raising the radius from 2 to B delays escape by ~log2(log2 B) items."""
    from distributedmandelbrot_tpu.ops import escape_smooth
    spec = TileSpec(-0.8, 0.1, 0.2, 0.2, width=64, height=64)
    cr, ci = grids(spec)
    nu = np.asarray(escape_smooth(cr, ci, max_iter=512, bailout=256.0))
    counts = np.asarray(ref.escape_counts(cr, ci, 512)).astype(float)
    esc = counts != 0
    # Escaping against B=256 happens ~3 iterations after |z|>2; allow slack.
    delta = nu[esc] - counts[esc]
    assert np.percentile(delta, 5) > -1.0 and np.percentile(delta, 95) < 6.0


def test_smooth_is_band_free_on_a_gradient():
    """Along a line crossing several integer-count bands, smooth values must
    be strictly monotone (no plateaus/banding) where counts are monotone."""
    from distributedmandelbrot_tpu.ops import escape_smooth
    # Walk outward on the real axis from near the set toward fast escape.
    cr = np.linspace(0.26, 1.8, 512)
    ci = np.zeros_like(cr)
    nu = np.asarray(escape_smooth(cr, ci, max_iter=256))
    assert (nu > 0).all()
    # Escape time decreases monotonically as c moves away from the set.
    assert (np.diff(nu) < 0).mean() > 0.99


def test_smooth_f64_path_and_tile_helper():
    from distributedmandelbrot_tpu.ops import compute_tile_smooth
    spec = TileSpec(-0.748, 0.09, 0.005, 0.005, width=32, height=32)
    nu = compute_tile_smooth(spec, 2000, dtype=np.float64)
    assert nu.shape == (32, 32) and nu.dtype == np.float64
    assert np.isfinite(nu).all()


def test_smooth_rgba_rendering():
    from distributedmandelbrot_tpu.viewer import smooth_to_rgba
    nu = np.array([[0.0, 1.5], [200.0, 255.9]])
    rgba = smooth_to_rgba(nu, 256)
    assert rgba.shape == (2, 2, 4)
    np.testing.assert_array_equal(rgba[0, 0], [0, 0, 0, 1])  # in-set black
    assert (rgba[..., 3] == 1).all()


# ---------------------------------------------------------------------------
# Julia family — capability extension reusing the shared recurrence.

JULIA_CS = [complex(-0.8, 0.156), complex(0.285, 0.01), complex(-0.4, 0.6)]


@pytest.mark.parametrize("c", JULIA_CS)
def test_julia_f64_matches_golden(c):
    from distributedmandelbrot_tpu.ops import escape_counts_julia
    spec = TileSpec(-1.5, -1.5, 3.0, 3.0, width=64, height=64)
    zr, zi = grids(spec)
    got = np.asarray(escape_counts_julia(zr, zi, c, max_iter=256))
    golden = ref.escape_counts_julia(zr, zi, c, 256)
    mismatch = (got != golden).mean()
    assert mismatch <= 5e-4, f"{mismatch:.2%} pixels diverge"


def test_julia_tile_end_to_end_uint8():
    from distributedmandelbrot_tpu.ops import compute_tile_julia
    spec = TileSpec(-1.5, -1.5, 3.0, 3.0, width=64, height=64)
    zr, zi = grids(spec)
    c = JULIA_CS[0]
    golden = ref.scale_counts_to_uint8(
        ref.escape_counts_julia(zr, zi, c, 256), 256).ravel()
    got = compute_tile_julia(spec, c, 256, dtype=np.float64)
    assert got.dtype == np.uint8 and got.shape == golden.shape
    mismatch = (got != golden).mean()
    assert mismatch <= 5e-4


def test_julia_c_zero_is_unit_disk():
    """c=0: |z| <= 1 never escapes; |z| > 1 escapes (squaring doubles the
    log-magnitude each step)."""
    from distributedmandelbrot_tpu.ops import escape_counts_julia
    zr = np.array([0.0, 0.5, 0.999, 1.5, 2.5])
    zi = np.zeros_like(zr)
    counts = np.asarray(escape_counts_julia(zr, zi, 0j, max_iter=256))
    assert (counts[:3] == 0).all()
    assert (counts[3:] > 0).all()


def test_julia_smooth_classification_and_reuse():
    """Julia smooth path: in-set iff integer Julia path says so, and
    sweeping c must NOT recompile (c is traced, not static)."""
    from distributedmandelbrot_tpu.ops import (escape_counts_julia,
                                               escape_smooth_julia)
    from distributedmandelbrot_tpu.ops.escape_time import _escape_smooth_jit
    spec = TileSpec(-1.5, -1.5, 3.0, 3.0, width=48, height=48)
    zr, zi = grids(spec)
    before = _escape_smooth_jit._cache_size()
    for c in JULIA_CS:
        nu = np.asarray(escape_smooth_julia(zr, zi, c, max_iter=128))
        counts = np.asarray(escape_counts_julia(zr, zi, c, max_iter=128))
        mismatch = ((nu == 0.0) != (counts == 0)).mean()
        assert mismatch <= 5e-4, f"c={c}: {mismatch:.2%} divergence"
    # One compilation serves all three constants (same shapes/dtype).
    assert _escape_smooth_jit._cache_size() - before <= 1


def test_interior_margin_rejects_unvalidated_dtypes():
    """The strict-by-margin guarantee is validated for f32/f64 only; an
    f16 input without an explicit margin must raise instead of silently
    using a margin below one ulp of the test polynomials (round-2
    advisor finding)."""
    import jax.numpy as jnp
    import pytest

    from distributedmandelbrot_tpu.ops.escape_time import mandelbrot_interior

    c = jnp.zeros((4, 4), jnp.float16)
    with pytest.raises(ValueError, match="no validated interior margin"):
        mandelbrot_interior(c, c)
    # An explicit margin opts in.
    assert bool(mandelbrot_interior(c, c, margin=1e-2).any())


def test_multibrot_interior_shares_margin_policy():
    """multibrot_interior follows the same one-policy margin resolution as
    mandelbrot_interior: unvalidated dtypes raise (round-3 verdict — the
    old ``.get(dtype, 1e-5)`` fallback silently broke the strict-by-margin
    guarantee for bf16/f16 callers), explicit margins opt in."""
    import jax.numpy as jnp
    import pytest

    from distributedmandelbrot_tpu.ops.escape_time import multibrot_interior

    for dt in (jnp.float16, jnp.bfloat16):
        c = jnp.zeros((4, 4), dt)
        with pytest.raises(ValueError, match="no validated interior margin"):
            multibrot_interior(c, c, power=3)
    c = jnp.zeros((4, 4), jnp.float16)
    assert bool(multibrot_interior(c, c, power=3, margin=1e-2).any())
    # Validated dtypes still classify the origin interior by default.
    c32 = jnp.zeros((4, 4), jnp.float32)
    assert bool(multibrot_interior(c32, c32, power=3).all())
