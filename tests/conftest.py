"""Test configuration: force the JAX CPU backend with 8 virtual devices.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU mesh instead (the standard substitute — mirrors how every piece
of the reference system is testable on loopback).  Must run before jax is
used anywhere, hence environment mutation at conftest import time.

Note: env vars alone are not enough on images where a sitecustomize
registers a remote-TPU PJRT plugin at interpreter start; that plugin's
backend init blocks on a network tunnel.  ``jax.config.update`` is applied
*before* any backend is initialized, which reliably restricts platform
selection, and the remote plugin's factory is dropped for good measure.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (env must be set first)

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

try:  # Drop any remotely-tunneled accelerator plugin registered at startup.
    import jax._src.xla_bridge as _xb

    for _plat in ("axon", "tpu"):
        _xb._backend_factories.pop(_plat, None)
except Exception:
    pass
