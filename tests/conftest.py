"""Test configuration: force the JAX CPU backend with 8 virtual devices.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU mesh instead (the standard substitute — mirrors how every piece
of the reference system is testable on loopback).  Must run before jax is
used anywhere, hence environment mutation at conftest import time.

Note: env vars alone are not enough on images where a sitecustomize
registers a remote-TPU PJRT plugin at interpreter start; that plugin's
backend init blocks on a network tunnel.  ``jax.config.update`` is applied
*before* any backend is initialized, which reliably restricts platform
selection, and the remote plugin's factory is dropped for good measure.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The CI lint gate (.github/workflows/check.yml) runs the analysis and
# callgraph suites on a jax-free interpreter; those tests never touch a
# backend, so a missing jax just skips the backend pinning below.
try:
    import jax  # noqa: E402  (env must be set first)
except ImportError:
    jax = None

if jax is not None:
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

try:  # Drop any remotely-tunneled accelerator plugin registered at startup.
    import jax._src.xla_bridge as _xb

    # Pop every factory FIRST: if the jax-internal attrs used below ever
    # change shape, the exception must not leave the tunnel-blocking
    # factories registered (the whole suite would hang at backend init).
    for _plat in ("axon", "tpu"):
        _xb._backend_factories.pop(_plat, None)
    for _plat in ("axon", "tpu"):
        # Keep the platform *name* known: jax.experimental.pallas registers
        # tpu-platform MLIR lowerings at import, and known_platforms() is
        # derived from the factory registry we just popped — without this,
        # the pallas import itself raises NotImplementedError and the
        # kernel can't even run in interpret mode.
        _xb._experimental_plugins.add(_plat)
except Exception:
    pass
