"""Compacted two-phase escape pipeline: bit-identity vs the plain kernel.

The pipeline (ops/compact_escape.py) is a measured NEGATIVE on the
current bench stack — XLA:TPU's per-lane gather/scatter/sort run at
0.6-2.7 GB/s there, costing more than the compute it saves (see
ROUND4_NOTES.md "Live-lane compaction") — so dispatch never selects it
by default.  It stays fully functional and bit-identical behind the
DMTPU_COMPACT opt-in because the resume kernel itself measured 520
Giter/s (2.3x the plain kernel's best big-call rate): on a stack with
healthy gather bandwidth the same pipeline is the floor-view win the
round-3 audit pointed at.  These tests pin the identity contract that
makes it safe to enable.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from distributedmandelbrot_tpu.ops.compact_escape import (  # noqa: E402
    _compact_escape, compact_capacity, compact_escape_batch,
    prefer_compaction)
from distributedmandelbrot_tpu.ops.pallas_escape import (  # noqa: E402
    PallasUnsupported, _pallas_escape_batch)


def _params(cx, cy, span, n, extra=()):
    s = span / (n - 1)
    return [cx - span / 2, cy - span / 2, s, s, *extra]


def _ref(params, mrds, k, n, mi, **kw):
    return np.asarray(_pallas_escape_batch(
        jnp.asarray(params, jnp.float32), jnp.asarray(mrds, jnp.int32),
        k=k, height=n, width=n, max_iter=mi, cycle_check=False,
        interpret=True, **kw))


def _out(params, mrds, k, n, mi, **kw):
    return np.asarray(compact_escape_batch(
        jnp.asarray(params, jnp.float32), jnp.asarray(mrds, jnp.int32),
        k=k, height=n, width=n, max_iter=mi, interpret=True, **kw))


N = 128
BOUNDARY = _params(-0.7436447, 0.1318252, 2e-3, N)   # no provable interior
FULLVIEW = _params(-0.5, 0.0, 3.0, N)                # interior + sky mix


def test_identity_boundary_and_mixed_budgets():
    """Mixed-budget batch across a boundary view and a set-crossing view:
    byte-identical to the plain batch kernel (the resume seam, per-lane
    budget retirement, and the scatter-back all exercised at once)."""
    params = [BOUNDARY, FULLVIEW]
    mrds = [[700], [650]]
    assert (_ref(params, mrds, 2, N, 700)
            == _out(params, mrds, 2, N, 700)).all()


def test_identity_shallow_tile_in_deep_batch():
    """A tile whose whole budget fits inside phase 1 must contribute no
    survivors (its unescaped lanes already classify in-set) while its
    batch-mate resumes past the seam."""
    params = [BOUNDARY, FULLVIEW]
    mrds = [[700], [200]]  # 200 - 1 < PHASE1_BUDGET
    assert (_ref(params, mrds, 2, N, 700)
            == _out(params, mrds, 2, N, 700)).all()


def test_identity_overflow_in_place_resume():
    """Capacity one block-grid on a boundary-dense view forces the
    overflow path: lanes past capacity resume in place over the original
    grid, still byte-identical."""
    params = [BOUNDARY]
    mrds = [[700]]
    ref = _ref(params, mrds, 1, N, 700)
    out = np.asarray(_compact_escape(
        jnp.asarray(params, jnp.float32), jnp.asarray(mrds, jnp.int32),
        k=1, height=N, width=N, max_iter=700, cap_lanes=4096,
        phase_budget=64, seg_steps=64, block_h=64, block_w=128, unroll=64,
        clamp=False, interior_check=True, julia=False, power=2,
        burning=False, interpret=True))
    assert (ref == out).all()


@pytest.mark.parametrize("mode", ["julia", "ship", "multibrot", "clamp"])
def test_identity_feature_matrix(mode):
    kw = {}
    params = [BOUNDARY]
    if mode == "julia":
        params = [_params(0.0, 0.0, 3.0, N, (-0.8, 0.156))]
        kw["julia"] = True
    elif mode == "ship":
        params = [_params(-1.7443, -0.0356, 0.01, N)]
        kw["burning"] = True
    elif mode == "multibrot":
        kw["power"] = 3
    elif mode == "clamp":
        kw["clamp"] = True
    mrds = [[700]]
    assert (_ref(params, mrds, 1, N, 700, **kw)
            == _out(params, mrds, 1, N, 700, **kw)).all()


def test_guards():
    """Structural rejections: probe-class budgets, phase-1-only budgets,
    unaligned phases — loud PallasUnsupported, never silent wrong output."""
    p = jnp.asarray([BOUNDARY], jnp.float32)
    with pytest.raises(PallasUnsupported, match="cycle probe"):
        compact_escape_batch(p, jnp.asarray([[8192]], jnp.int32), k=1,
                             height=N, width=N, max_iter=8192,
                             interpret=True)
    with pytest.raises(PallasUnsupported, match="phase 1"):
        compact_escape_batch(p, jnp.asarray([[200]], jnp.int32), k=1,
                             height=N, width=N, max_iter=200,
                             interpret=True)
    with pytest.raises(PallasUnsupported, match="unroll-aligned"):
        compact_escape_batch(p, jnp.asarray([[700]], jnp.int32), k=1,
                             height=N, width=N, max_iter=700,
                             phase_budget=100, interpret=True)
    with pytest.raises(PallasUnsupported, match="divisible"):
        compact_escape_batch(p, jnp.asarray([[700]], jnp.int32), k=1,
                             height=100, width=N, max_iter=700,
                             interpret=True)


def test_sharded_dispatch_opt_in(monkeypatch):
    """The production sharded batch path routes through the compacted
    dispatch (policy stubbed permissive — the real gate needs 512^2+
    tiles, too slow for interpret mode; the policy itself is pinned in
    test_capacity_and_policy) and stays byte-identical to the default
    route.  The budget buckets past the probe threshold (true cap 700
    -> compile cap 1024), exercising the already-resolved cycle_check
    forwarding; the explicit-cap slice is covered directly in
    test_bucketed_cap_forwards_resolved_probe."""
    import distributedmandelbrot_tpu.ops.compact_escape as CE
    from distributedmandelbrot_tpu.parallel import tile_mesh
    from distributedmandelbrot_tpu.parallel.sharding import (
        batched_escape_pixels_pallas)

    mesh = tile_mesh(8)
    k = max(2, mesh.devices.size)
    s = 2e-3 / (N - 1)
    starts = np.asarray([[-0.7436447 - 1e-3 + 1e-4 * i,
                          0.1318252 - 1e-3, s] for i in range(k)])
    mrds = np.full(k, 700, np.int64)
    base = batched_escape_pixels_pallas(mesh, starts, mrds, definition=N)
    routed = []
    real = CE.compact_escape_batch

    def spy(*a, **kw):
        routed.append(True)
        return real(*a, **kw)

    monkeypatch.setattr(CE, "prefer_compaction", lambda *a: True)
    monkeypatch.setattr(CE, "compact_escape_batch", spy)
    out = batched_escape_pixels_pallas(mesh, starts, mrds, definition=N)
    assert routed, "compact branch was not taken — vacuous comparison"
    assert (base == out).all()


def test_bucketed_cap_forwards_resolved_probe():
    """True caps below CYCLE_CHECK_MIN_ITER that bucket to a compile
    cap at/above it (since round 5 the live band is 513-1023 -> bucket
    1024; here exercised at an explicit 4096 cap): the dispatch must
    forward the probe policy resolved from the TRUE cap (False) rather
    than re-resolving against the bucketed cap, which would arm the
    probe and reject the whole slice (round-4 review finding)."""
    params = jnp.asarray([BOUNDARY], jnp.float32)
    mrds = jnp.asarray([[300]], jnp.int32)  # cheap per-lane budget
    ref = np.asarray(_pallas_escape_batch(
        params, mrds, k=1, height=N, width=N, max_iter=4096,
        cycle_check=False, interpret=True))
    out = np.asarray(compact_escape_batch(
        params, mrds, k=1, height=N, width=N, max_iter=4096,
        cycle_check=False, interpret=True))
    assert (ref == out).all()
    with pytest.raises(PallasUnsupported, match="cycle probe"):
        compact_escape_batch(params, mrds, k=1, height=N, width=N,
                             max_iter=4096, interpret=True)


def test_env_opt_in_parses():
    """DMTPU_COMPACT=1 flips the import-time opt-in (the policy gate the
    monkeypatch-based tests bypass) — checked in a subprocess because
    the flag is read at module import."""
    import os
    import subprocess
    import sys

    code = ("import distributedmandelbrot_tpu.ops.compact_escape as CE;"
            "print(CE._COMPACT_OPTED_IN and "
            "CE.prefer_compaction(900, 1 << 24))")
    env = dict(os.environ, DMTPU_COMPACT="1", JAX_PLATFORMS="cpu",
               PALLAS_AXON_POOL_IPS="")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-300:]
    assert out.stdout.strip().endswith("True")


def test_capacity_and_policy():
    """Capacity aligns to whole (32, 128) block grids; the dispatch
    policy is opt-in only (measured negative on the bench stack) and
    never selects probe-class or phase-1-only budgets even when opted
    in."""
    assert compact_capacity(16 * 1024 * 1024) == 4 * 1024 * 1024
    assert compact_capacity(100) == 32 * 128
    assert compact_capacity(4097 * 4) % (32 * 128) == 0
    import distributedmandelbrot_tpu.ops.compact_escape as CE
    assert not prefer_compaction(900, 1 << 24)  # no opt-in
    try:
        CE._COMPACT_OPTED_IN = True
        assert prefer_compaction(900, 1 << 24)
        assert not prefer_compaction(2000, 1 << 24)   # probe class (r5:
        # the strided probe's threshold dropped to 1024, shrinking the
        # opt-in band to 513..1023 — at probe-class budgets the default
        # dispatch carries the probe, which the resume kernel cannot)
        assert not prefer_compaction(8192, 1 << 24)   # probe class
        assert not prefer_compaction(300, 1 << 24)    # fits phase 1
        assert not prefer_compaction(900, 1 << 10)    # too few pixels
    finally:
        CE._COMPACT_OPTED_IN = False
