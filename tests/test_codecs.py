import struct

import numpy as np
import pytest

from distributedmandelbrot_tpu import codecs
from distributedmandelbrot_tpu.codecs import RAW, RLE
from distributedmandelbrot_tpu.core import CHUNK_PIXELS, Chunk


def reference_rle_decode(body: bytes) -> bytes:
    """Independent decoder following the viewer's record format
    (DistributedMandelbrotViewer.py:35-50): uint32 LE count + uint8 value."""
    out = bytearray()
    i = 0
    while i < len(body):
        count, val = struct.unpack("<IB", body[i:i + 5])
        out.extend([val] * count)
        i += 5
    return bytes(out)


def test_raw_roundtrip():
    data = np.random.default_rng(0).integers(0, 256, 1000, dtype=np.uint8)
    body = RAW.encode(data)
    assert body == data.tobytes()
    np.testing.assert_array_equal(RAW.decode(body, 1000), data)


def test_rle_roundtrip_and_format():
    data = np.array([5, 5, 5, 0, 0, 7], dtype=np.uint8)
    body = RLE.encode(data)
    assert body == struct.pack("<IB", 3, 5) + struct.pack("<IB", 2, 0) + \
        struct.pack("<IB", 1, 7)
    np.testing.assert_array_equal(RLE.decode(body, 6), data)
    assert reference_rle_decode(body) == data.tobytes()


def test_rle_single_run():
    data = np.zeros(CHUNK_PIXELS, dtype=np.uint8)
    body = RLE.encode(data)
    assert body == struct.pack("<IB", CHUNK_PIXELS, 0)
    assert RLE.encoded_size(data) == 5


def test_rle_decode_rejects_zero_run():
    with pytest.raises(ValueError):
        RLE.decode(struct.pack("<IB", 0, 1), 0)


def test_rle_decode_rejects_wrong_total():
    body = struct.pack("<IB", 3, 9)
    with pytest.raises(ValueError):
        RLE.decode(body, 4)
    with pytest.raises(ValueError):
        RLE.decode(body, 2)


def test_pick_min_selects_rle_for_flat_data():
    payload = codecs.serialize(np.zeros(CHUNK_PIXELS, dtype=np.uint8))
    assert payload[0] == 0x01
    assert len(payload) == 6  # code byte + one 5-byte record


def test_pick_min_selects_raw_for_noise():
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, 4096, dtype=np.uint8)
    payload = codecs.serialize(data)
    assert payload[0] == 0x00
    np.testing.assert_array_equal(codecs.deserialize(payload, 4096), data)


def test_roundtrip_property():
    rng = np.random.default_rng(2)
    for _ in range(10):
        # Run-heavy data to exercise RLE selection.
        runs = rng.integers(1, 50, size=rng.integers(1, 100))
        vals = rng.integers(0, 4, size=runs.size).astype(np.uint8)
        data = np.repeat(vals, runs)
        payload = codecs.serialize(data)
        np.testing.assert_array_equal(codecs.deserialize(payload, data.size),
                                      data)


def test_chunk_classification():
    assert Chunk.never(4, 0, 0).is_never
    assert not Chunk.never(4, 0, 0).is_immediate
    assert Chunk.immediate(4, 1, 2).is_immediate
    data = np.zeros(CHUNK_PIXELS, dtype=np.uint8)
    data[123] = 9
    c = Chunk(4, 0, 0, data)
    assert not c.is_never and not c.is_immediate


def test_chunk_serialize_roundtrip():
    data = np.zeros(CHUNK_PIXELS, dtype=np.uint8)
    data[::7] = 3
    c = Chunk(4, 2, 1, data)
    np.testing.assert_array_equal(Chunk.deserialize_data(c.serialize()), data)


def test_chunk_copies_caller_buffer():
    """A frozen Chunk must not alias the caller's buffer — workers reuse
    their pixel buffers between tiles."""
    buf = np.zeros(CHUNK_PIXELS, dtype=np.uint8)
    c = Chunk(4, 0, 0, buf)
    buf[0] = 7
    assert c.data[0] == 0 and c.is_never


def test_chunk_validates_size_and_indices():
    with pytest.raises(ValueError):
        Chunk(4, 0, 0, np.zeros(10, dtype=np.uint8))
    with pytest.raises(ValueError):
        Chunk(4, 4, 0, np.zeros(CHUNK_PIXELS, dtype=np.uint8))
