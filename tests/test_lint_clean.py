"""Tier-1 gate: the repo itself passes ``dmtpu check`` with zero
unsuppressed findings, fast, and without ever importing jax.

This is the enforcement end of the analysis package: every future PR
that breaks lock discipline, re-types a wire format, blocks the event
loop, or dirties a traced function fails here, in a sub-second
subprocess.  Runs the real CLI in a fresh interpreter so the no-jax
claim is measured, not assumed.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GATE_SCRIPT = """\
import json, sys
from distributedmandelbrot_tpu.cli import main
rc = main(["check", "--json"])
assert "jax" not in sys.modules, "dmtpu check must not import jax"
sys.exit(rc)
"""


def test_repo_is_lint_clean_fast_and_jax_free():
    t0 = time.monotonic()
    result = subprocess.run(
        [sys.executable, "-c", GATE_SCRIPT],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    elapsed = time.monotonic() - t0
    assert result.returncode == 0, \
        f"dmtpu check found problems:\n{result.stdout}\n{result.stderr}"
    doc = json.loads(result.stdout)
    assert doc["counts"]["total"] == 0, doc["findings"]
    assert doc["stale_baseline"] == []
    assert elapsed < 5.0, f"gate took {elapsed:.1f}s (budget 5s)"


def test_v2_families_are_registered_and_listed():
    # The catalogue (and thus --list-rules / --rules) must cover the v2
    # families; family names must expand to their rule ids.
    from distributedmandelbrot_tpu import analysis
    families = {r.family for r in analysis.all_rules().values()}
    assert {"proto", "res", "obs"} <= families
    assert "obs-name" in analysis.all_rules()
    expanded = analysis.expand_rule_ids(["proto", "res", "obs-name"])
    assert {"proto-dispatch", "proto-frames", "proto-exact-read",
            "res-thread-join", "res-socket-close", "res-queue-unbounded",
            "res-shutdown", "obs-name"} <= set(expanded)


def test_v3_taint_and_exc_families_are_registered():
    # The dataflow-backed families ride in the same gate: the repo stays
    # clean with them on, and family names expand for --rules taint,exc.
    from distributedmandelbrot_tpu import analysis
    families = {r.family for r in analysis.all_rules().values()}
    assert {"taint", "exc"} <= families
    expanded = analysis.expand_rule_ids(["taint", "exc"])
    assert {"taint-alloc", "taint-index", "taint-loop", "taint-struct",
            "exc-leak", "exc-swallow"} <= set(expanded)
    for rule in analysis.all_rules().values():
        assert rule.severity in ("error", "warning")


def test_v4_fsm_family_is_registered():
    # The model-checking family rides in the same gate: the protocol
    # automata explore clean on the real tree with it on.
    from distributedmandelbrot_tpu import analysis
    families = {r.family for r in analysis.all_rules().values()}
    assert "fsm" in families
    expanded = analysis.expand_rule_ids(["fsm"])
    assert {"fsm-dual", "fsm-deadlock", "fsm-cap-gate",
            "fsm-dead-arm"} <= set(expanded)
    assert "obs-dead" in analysis.all_rules()


def test_baseline_has_no_entries():
    # The v2 rollout fixed or inline-suppressed every true positive; the
    # committed baseline must stay empty so new findings always surface.
    path = os.path.join(REPO, "tools", "lint_baseline.json")
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    assert doc["findings"] == []


def test_metric_name_literals_are_registered():
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_metrics.py"),
         "--offline", "--names", "--dead"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert result.returncode == 0, \
        f"check_metrics --names --dead failed:\n" \
        f"{result.stdout}\n{result.stderr}"
    assert "names:" in result.stdout
    assert "dead:" in result.stdout
