"""Stateful property test of the TileScheduler (hypothesis).

The unit tests pin known interleavings; this machine explores random
sequences of the real farm operations — grants, claims, finishes,
releases, abandonments, time advance, sweeps, save-failure reopens —
against a model, checking after every step the invariants the
at-least-once/dedup design promises (survey §5.2/§5.3):

- a completed tile is never granted again (unless explicitly reopened)
- a tile never completes twice (claim tokens dedup late submissions)
- grants never exceed one live lease/claim per tile
- whenever work remains and no lease blocks it, acquire() makes progress
- after quiescence (expire + drain), every tile is completed exactly once
"""

from __future__ import annotations

import pytest

pytest.importorskip("hypothesis")

import hypothesis.strategies as st
from hypothesis import settings
from hypothesis.stateful import (RuleBasedStateMachine, invariant,
                                 precondition, rule)

from distributedmandelbrot_tpu.coordinator.clock import ManualClock
from distributedmandelbrot_tpu.coordinator.scheduler import TileScheduler
from distributedmandelbrot_tpu.core.workload import LevelSetting

LEASE = 10.0


class SchedulerMachine(RuleBasedStateMachine):
    def __init__(self):
        super().__init__()
        self.clock = ManualClock()
        self.sched = TileScheduler([LevelSetting(2, 50), LevelSetting(3, 70)],
                                   lease_timeout=LEASE, clock=self.clock)
        self.total = self.sched.total_tiles
        self.leased: dict = {}   # key -> workload ("worker holds lease")
        self.claims: dict = {}   # key -> (workload, token): echo accepted,
        #                          payload in flight (may expire mid-flight)
        self.completed: set = set()

    # -- worker-side operations -------------------------------------------

    @rule()
    def acquire(self):
        w = self.sched.acquire()
        if w is not None:
            assert w.key not in self.completed, \
                "completed tile granted again"
            self.leased[w.key] = w

    @precondition(lambda self: self.leased)
    @rule(data=st.data())
    def claim_result(self, data):
        """The 16-byte echo arrives: lease -> claim (payload in flight).
        While claimed, no second claim for the tile may exist."""
        key = data.draw(st.sampled_from(sorted(self.leased)))
        w = self.leased.pop(key)
        token = self.sched.claim(w)
        if token is None:
            return  # lease expired under us — tile will be re-granted
        assert self.sched.claim(w) is None  # lease consumed by the claim
        self.claims[key] = (w, token)

    @precondition(lambda self: self.claims)
    @rule(data=st.data())
    def finish_claimed(self, data):
        """The payload lands; expired-claim finishes must requeue, not
        complete."""
        key = data.draw(st.sampled_from(sorted(self.claims)))
        w, token = self.claims.pop(key)
        ok = self.sched.finish_claim(w, token)
        if ok:
            assert key not in self.completed, "tile completed twice"
            self.completed.add(key)

    @precondition(lambda self: self.claims)
    @rule(data=st.data())
    def finish_with_stale_token(self, data):
        """A dawdler's finish with a WRONG token must be rejected and
        must not consume the live claim."""
        key = data.draw(st.sampled_from(sorted(self.claims)))
        w, token = self.claims[key]
        assert self.sched.finish_claim(w, token + 1_000_000) is False
        # The live claim is untouched: the real token still works later.

    @precondition(lambda self: self.claims)
    @rule(data=st.data())
    def release_claimed(self, data):
        """Upload aborts; the tile must become grantable again."""
        key = data.draw(st.sampled_from(sorted(self.claims)))
        w, token = self.claims.pop(key)
        self.sched.release_claim(w, token)

    @precondition(lambda self: self.leased)
    @rule(data=st.data())
    def abandon(self, data):
        # Worker crash: drop the lease on the floor (expiry reclaims it).
        key = data.draw(st.sampled_from(sorted(self.leased)))
        del self.leased[key]

    # -- coordinator-side operations --------------------------------------

    @rule()
    def advance_past_expiry(self):
        self.clock.advance(LEASE + 1.0)
        # Everything outstanding just expired; workers' in-hand leases
        # and claims are now stale (their finishes must requeue/reject —
        # exercised by finish_claimed drawing an expired claim).
        self.leased.clear()

    @rule()
    def small_advance(self):
        self.clock.advance(1.0)

    @rule()
    def sweep(self):
        self.sched.sweep()

    @precondition(lambda self: self.completed)
    @rule(data=st.data())
    def reopen_failed_save(self, data):
        key = data.draw(st.sampled_from(sorted(self.completed)))
        from distributedmandelbrot_tpu.core.workload import Workload
        # None mrd: the null-wildcard identity disk-seeded entries use.
        self.sched.reopen(Workload(key[0], None, key[1], key[2]))
        self.completed.discard(key)

    # -- invariants --------------------------------------------------------

    @invariant()
    def counts_agree(self):
        assert self.sched.completed_count == len(self.completed)
        assert self.sched.completed_count <= self.total
        assert self.sched.is_complete() == (len(self.completed)
                                            == self.total)

    @invariant()
    def progress_is_possible(self):
        """If nothing is leased/claimed and work remains, acquire() must
        grant (no lost tiles)."""
        if (not self.leased and not self.claims
                and self.sched.outstanding_leases == 0
                and len(self.completed) < self.total):
            w = self.sched.acquire()
            assert w is not None, "work remains but nothing grantable"
            self.leased[w.key] = w

    def teardown(self):
        """Drive to quiescence: every tile must complete exactly once."""
        guard = 0
        while not self.sched.is_complete():
            w = self.sched.acquire()
            if w is None:
                self.clock.advance(LEASE + 1.0)
                self.sched.sweep()
                guard += 1
                assert guard < 1000, "farm cannot drain"
                continue
            assert w.key not in self.completed
            assert self.sched.complete(w)
            self.completed.add(w.key)
        assert len(self.completed) == self.total


TestSchedulerProperties = SchedulerMachine.TestCase
TestSchedulerProperties.settings = settings(
    max_examples=60, stateful_step_count=60, deadline=None)
