"""Server-side rendering: golden parity, PNG codec guards, cache tiers.

The headline contract is bit-parity: the bytes a gateway serves for a
rendered tile must equal the bytes the viewer would have produced by
fetching the raw tile and colormapping it locally.  Parity holds by
construction (both paths share one quantization + LUT), and these tests
pin the construction — over every escape value, every registered
colormap, and end-to-end through a real replica fleet.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from distributedmandelbrot_tpu.core.chunk import Chunk
from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
from distributedmandelbrot_tpu.net import framing
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.serve import render
from distributedmandelbrot_tpu.serve.cache import (DecodedTileCache,
                                                   RenderedTileCache)
from distributedmandelbrot_tpu.storage.backends import (MemoryObjectStore,
                                                        ObjectStoreBackend)
from distributedmandelbrot_tpu.storage.store import ChunkStore
from distributedmandelbrot_tpu.utils.metrics import Counters
from distributedmandelbrot_tpu.viewer.client import DataClient, FetchStatus

from distributedmandelbrot_tpu.loadgen.replicas import GatewayFleet


def _tile_pixels() -> np.ndarray:
    """A full-size tile touching every escape value, plus in-set runs."""
    pixels = np.tile(np.arange(256, dtype=np.uint8), CHUNK_PIXELS // 256)
    pixels[:4096] = 0  # an in-set (forced-black) band
    return pixels


# -- golden parity ----------------------------------------------------------

def test_lut_render_matches_viewer_float_pipeline_all_values():
    pytest.importorskip("matplotlib")
    values = np.arange(256, dtype=np.uint8).reshape(16, 16)
    for colormap in proto.COLORMAPS.values():
        via_lut = render.render_tile_rgba8(values, colormap)
        via_floats = render.to_rgba8(render.value_to_rgba(values, colormap))
        assert np.array_equal(via_lut, via_floats), colormap
    # Value 0 (in-set) is painted opaque black in every colormap.
    assert np.array_equal(render.value_lut("jet")[0], [0, 0, 0, 255])


def test_png_roundtrip_is_lossless_and_deterministic():
    pytest.importorskip("matplotlib")
    rng = np.random.default_rng(5)
    values = rng.integers(0, 256, size=(64, 64), dtype=np.uint8)
    body = render.render_tile_png(values, "viridis")
    assert body == render.render_tile_png(values, "viridis")
    rgba = render.decode_rendered_png(body)
    assert np.array_equal(rgba, render.render_tile_rgba8(values, "viridis"))


def test_server_rendered_bytes_equal_viewer_rendered_bytes_e2e():
    """The acceptance criterion, through real sockets: fetch the raw
    tile and the server-rendered PNG from a replica fleet, render the
    raw tile viewer-side, compare bytes."""
    pytest.importorskip("matplotlib")
    pixels = _tile_pixels()
    kv = MemoryObjectStore()
    ChunkStore(backend=ObjectStoreBackend(kv)).save(Chunk(2, 1, 0, pixels))
    with GatewayFleet(kv, replicas=1) as fleet:
        host, port = fleet.addresses[0]
        with DataClient(host, port) as client:
            raw, status = client.fetch(2, 1, 0)
            assert status is FetchStatus.OK
            body, status = client.fetch_render(2, 1, 0,
                                               proto.COLORMAP_PLASMA)
            assert status is FetchStatus.OK
            # Second fetch is a rendered-cache hit; bytes must not drift.
            again, _ = client.fetch_render(2, 1, 0, proto.COLORMAP_PLASMA)
    assert np.array_equal(raw, pixels)
    viewer_rgba = render.to_rgba8(render.value_to_rgba(raw, "plasma"))
    server_rgba = render.decode_rendered_png(body)
    assert np.array_equal(server_rgba, viewer_rgba)
    assert again == body
    assert fleet.counter(obs_names.GATEWAY_RENDER_CACHE_HITS) >= 1
    # The hot body is the bandwidth story: tiny next to the raw payload.
    assert len(body) < CHUNK_PIXELS // 10


def test_render_unavailable_and_overload_statuses_flow_to_client():
    kv = MemoryObjectStore()
    with GatewayFleet(kv, replicas=1, rate=0.001, burst=1.0) as fleet:
        host, port = fleet.addresses[0]
        with DataClient(host, port) as client:
            # Burst token pays for the first query: a store miss.
            body, status = client.fetch_render(1, 0, 0)
            assert body is None and status is FetchStatus.NOT_AVAILABLE
            # Bucket empty: admission control sheds before resolving.
            body, status = client.fetch_render(1, 0, 0)
            assert body is None and status is FetchStatus.OVERLOADED


# -- PNG decoder guards -----------------------------------------------------

def test_png_decoder_rejects_bombs_and_foreign_shapes():
    import struct
    import zlib

    values = np.zeros((8, 8), dtype=np.uint8)
    body = render.render_tile_png(values)

    with pytest.raises(ValueError):
        render.decode_rendered_png(b"GIF89a" + body)

    # IHDR promises 8x8 but IDAT inflates to a megabyte: the bounded
    # inflate must refuse without materializing the bomb.
    bomb_idat = zlib.compress(b"\x00" * (1 << 20))
    pos = len(render.PNG_SIGNATURE)
    chunks = []
    data = body
    while pos + 8 <= len(data):
        (length,) = struct.unpack_from(">I", data, pos)
        tag = data[pos + 4:pos + 8]
        chunks.append((tag, data[pos + 8:pos + 8 + length]))
        pos += 12 + length
    rebuilt = render.PNG_SIGNATURE
    for tag, chunk_body in chunks:
        if tag == b"IDAT":
            chunk_body = bomb_idat
        rebuilt += (struct.pack(">I", len(chunk_body)) + tag + chunk_body
                    + struct.pack(">I", zlib.crc32(tag + chunk_body)))
    with pytest.raises(ValueError, match="IHDR promises|expected"):
        render.decode_rendered_png(rebuilt)

    # Truthful truecolor PNG: refused as a foreign shape, not decoded.
    ihdr = struct.pack(">IIBBBBB", 8, 8, 8, 2, 0, 0, 0)
    foreign = render.PNG_SIGNATURE + b"".join(
        struct.pack(">I", len(b)) + t + b
        + struct.pack(">I", zlib.crc32(t + b))
        for t, b in ((b"IHDR", ihdr), (b"PLTE", b"\x00" * 768),
                     (b"IDAT", zlib.compress(b"\x00" * (8 * 25))),
                     (b"IEND", b"")))
    with pytest.raises(ValueError, match="unsupported PNG shape"):
        render.decode_rendered_png(foreign)


def test_render_rejects_non_square_pixel_counts():
    with pytest.raises(ValueError, match="square"):
        render.render_tile_png(np.zeros(37, dtype=np.uint8))


# -- rendered-tile cache tier ----------------------------------------------

def test_rendered_cache_lru_counters_and_gauge():
    counters = Counters()
    cache = RenderedTileCache(capacity=2, counters=counters)
    k1, k2, k3 = (1, 0, 0, 0), (2, 0, 0, 0), (2, 1, 0, 1)
    assert cache.get(k1) is None
    cache.put(k1, b"one")
    cache.put(k2, b"two")
    assert cache.get(k1) == b"one"  # refreshes k1; k2 is now LRU
    cache.put(k3, b"three")
    assert len(cache) == 2
    assert cache.get(k2) is None  # evicted
    assert counters.get(obs_names.GATEWAY_RENDER_CACHE_EVICTIONS) == 1
    hits = counters.get(obs_names.GATEWAY_RENDER_CACHE_HITS)
    misses = counters.get(obs_names.GATEWAY_RENDER_CACHE_MISSES)
    assert (hits, misses) == (1, 2)
    gauges = counters.registry.snapshot()["gauges"]
    assert gauges[obs_names.GAUGE_RENDER_HIT_RATIO] == pytest.approx(
        hits / (hits + misses))


# -- promotion-time RLE recompression ---------------------------------------

class _RawPayloadStore:
    """Stub store handing back raw-codec payloads (a legacy data dir)."""

    def __init__(self, pixels: np.ndarray) -> None:
        self.payload = bytes([0x00]) + pixels.tobytes()

    def load_payload(self, level, i, j):
        return self.payload


def test_promotion_recompresses_raw_runs_and_counts_savings():
    # Interior-dominated tile: estimate_ratio's histogram pre-filter
    # demands one escape count hold most of the tile before it pays for
    # an exact run count (see codecs/rle.py).
    pixels = np.full(CHUNK_PIXELS, 200, dtype=np.uint8)
    pixels[:4096] = np.repeat(np.arange(16, dtype=np.uint8), 256)
    counters = Counters()
    cache = DecodedTileCache(_RawPayloadStore(pixels), capacity=4,
                             counters=counters)
    entry = cache.load((1, 0, 0))
    assert entry.payload[0] != 0x00  # re-encoded away from Raw
    assert len(entry.payload) < len(pixels.tobytes()) // 100
    assert np.array_equal(entry.pixels, pixels)  # still decodes intact
    assert counters.get(obs_names.SERVE_RLE_RECOMPRESSIONS) == 1
    saved = counters.get(obs_names.SERVE_RLE_BYTES_SAVED)
    assert saved == len(pixels) + 1 - len(entry.payload)


def test_promotion_skips_incompressible_and_disabled():
    rng = np.random.default_rng(9)
    noise = rng.integers(0, 256, size=CHUNK_PIXELS, dtype=np.uint8)
    counters = Counters()
    cache = DecodedTileCache(_RawPayloadStore(noise), capacity=4,
                             counters=counters)
    entry = cache.load((1, 0, 0))
    assert entry.payload == bytes([0x00]) + noise.tobytes()  # untouched
    assert counters.get(obs_names.SERVE_RLE_SKIPPED) == 1
    assert counters.get(obs_names.SERVE_RLE_RECOMPRESSIONS) == 0

    runs = np.repeat(np.arange(16, dtype=np.uint8), CHUNK_PIXELS // 16)
    off = DecodedTileCache(_RawPayloadStore(runs), capacity=4,
                           recompress_min_ratio=0.0, counters=Counters())
    assert off.load((1, 0, 0)).payload[0] == 0x00  # pass disabled


def test_gateway_render_magic_never_validates_as_level():
    assert not proto.query_in_range(proto.GATEWAY_RENDER_MAGIC, 0, 0)
    assert not proto.query_in_range(proto.GATEWAY_BATCH_MAGIC, 0, 0)
    with pytest.raises(framing.ProtocolError):
        proto.validate_colormap(0x77)
    for cid in proto.COLORMAPS:
        assert proto.validate_colormap(cid) == cid
