"""Wire-conformance tests against live loopback servers, byte-level where it
matters (a third-party client written to the reference protocol must
interoperate)."""

import socket
import struct

import numpy as np
import pytest

from distributedmandelbrot_tpu.core import CHUNK_PIXELS, LevelSetting, Workload
from distributedmandelbrot_tpu.net import framing
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.viewer import DataClient, FetchStatus
from distributedmandelbrot_tpu.worker import DistributerClient

from harness import CoordinatorHarness


@pytest.fixture
def farm(tmp_path):
    with CoordinatorHarness(str(tmp_path), [LevelSetting(2, 64)]) as h:
        yield h


def raw_conn(port):
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


def test_request_grant_bytes(farm):
    """Purpose 0x00 -> 0x10 + 16B workload (level,mrd,i,j as u32 LE)."""
    with raw_conn(farm.distributer_port) as s:
        s.sendall(b"\x00")
        assert framing.recv_byte(s) == 0x10
        level, mrd, i, j = struct.unpack("<IIII", framing.recv_exact(s, 16))
        assert (level, mrd, i, j) == (2, 64, 0, 0)


def test_request_exhaustion_returns_not_available(farm):
    client = DistributerClient("127.0.0.1", farm.distributer_port)
    grants = [client.request() for _ in range(4)]
    assert all(w is not None for w in grants)
    with raw_conn(farm.distributer_port) as s:
        s.sendall(b"\x00")
        assert framing.recv_byte(s) == 0x11


def test_response_roundtrip_and_dedup(farm):
    client = DistributerClient("127.0.0.1", farm.distributer_port)
    w = client.request()
    zeros = np.zeros(CHUNK_PIXELS, dtype=np.uint8)
    # Byte-level submit: purpose 0x01, 16B echo, expect 0x20, stream pixels.
    with raw_conn(farm.distributer_port) as s:
        s.sendall(b"\x01" + w.to_wire())
        assert framing.recv_byte(s) == 0x20
        s.sendall(zeros.tobytes())
    farm.wait_saves_settled(expected_accepted=1)
    # Duplicate submission is rejected with 0x21.
    with raw_conn(farm.distributer_port) as s:
        s.sendall(b"\x01" + w.to_wire())
        assert framing.recv_byte(s) == 0x21


def test_unknown_result_rejected(farm):
    stray = Workload(2, 64, 1, 1)
    with raw_conn(farm.distributer_port) as s:
        s.sendall(b"\x01" + stray.to_wire())
        assert framing.recv_byte(s) == 0x21


def test_wrong_max_iter_rejected_wildcard_accepted(farm):
    client = DistributerClient("127.0.0.1", farm.distributer_port)
    w = client.request()
    wrong = Workload(w.level, 999, w.index_real, w.index_imag)
    assert not client.submit(wrong, np.zeros(CHUNK_PIXELS, np.uint8))
    # max_iter=0 is not a wildcard; only in-memory None is — which can't go
    # on the wire, so wire clients must echo exactly.
    still = Workload(w.level, w.max_iter, w.index_real, w.index_imag)
    assert client.submit(still, np.zeros(CHUNK_PIXELS, np.uint8))


def test_dataserver_statuses_and_payload(farm):
    client = DistributerClient("127.0.0.1", farm.distributer_port)
    data_client = DataClient("127.0.0.1", farm.dataserver_port)

    # Not yet computed -> NOT_AVAILABLE (0x02).
    pixels, status = data_client.fetch(2, 0, 0)
    assert status is FetchStatus.NOT_AVAILABLE and pixels is None

    # Invalid query (index >= level) -> REJECT (0x01).
    _, status = data_client.fetch(2, 2, 0)
    assert status is FetchStatus.REJECTED

    # Complete one tile, then fetch it.
    w = client.request()
    ones = np.ones(CHUNK_PIXELS, dtype=np.uint8)
    assert client.submit(w, ones)
    farm.wait_saves_settled(expected_accepted=1)
    pixels, status = data_client.fetch(w.level, w.index_real, w.index_imag)
    assert status is FetchStatus.OK
    np.testing.assert_array_equal(pixels, ones)


def test_dataserver_payload_bytes_are_length_prefixed_codec(farm):
    """Byte-level: status 0x00, u32 length, then code byte + body — an
    all-ones chunk must arrive as a single 5-byte RLE record."""
    client = DistributerClient("127.0.0.1", farm.distributer_port)
    w = client.request()
    client.submit(w, np.ones(CHUNK_PIXELS, dtype=np.uint8))
    farm.wait_saves_settled(expected_accepted=1)
    with raw_conn(farm.dataserver_port) as s:
        s.sendall(struct.pack("<III", w.level, w.index_real, w.index_imag))
        assert framing.recv_byte(s) == 0x00
        length = framing.recv_u32(s)
        payload = framing.recv_exact(s, length)
    assert payload[0] == 0x01  # RLE codec
    count, value = struct.unpack("<IB", payload[1:6])
    assert (count, value) == (CHUNK_PIXELS, 1)
    assert length == 6


def test_batch_request_and_response(farm):
    client = DistributerClient("127.0.0.1", farm.distributer_port)
    batch = client.request_batch(3)
    assert len(batch) == 3
    assert len({w.key for w in batch}) == 3
    results = [(w, np.full(CHUNK_PIXELS, 2, dtype=np.uint8)) for w in batch]
    assert client.submit_batch(results) == [True, True, True]
    farm.wait_saves_settled(expected_accepted=3)
    # Remaining tile via single path, then exhaustion.
    assert len(client.request_batch(10)) == 1
    assert client.request_batch(1) == []


def test_servers_survive_malformed_clients(farm):
    """Hostile/broken clients — unknown purpose bytes, truncated frames,
    mid-frame disconnects, random garbage — must never take down either
    accept loop: a well-behaved client still gets served afterward."""
    rng = np.random.default_rng(7)
    attacks_distributer = [
        b"\xff",                      # unknown purpose byte
        b"",                          # connect-then-close
        b"\x01" + b"\x00" * 7,        # response purpose, truncated echo
        bytes(rng.integers(0, 256, size=64, dtype=np.uint8)),  # garbage
    ]
    for payload in attacks_distributer:
        with raw_conn(farm.distributer_port) as s:
            s.sendall(payload) if payload else None
            # server may reply or just drop us; either way it must not die
            s.settimeout(2)
            try:
                s.recv(64)
            except (socket.timeout, ConnectionError, OSError):
                pass
    attacks_dataserver = [
        b"\x01\x02",                  # truncated 12-byte query
        bytes(rng.integers(0, 256, size=12, dtype=np.uint8)),  # random query
        b"",
    ]
    for payload in attacks_dataserver:
        with raw_conn(farm.dataserver_port) as s:
            s.sendall(payload) if payload else None
            s.settimeout(2)
            try:
                s.recv(64)
            except (socket.timeout, ConnectionError, OSError):
                pass
    # Both servers still alive and correct for a legitimate client.
    wl = DistributerClient("127.0.0.1", farm.distributer_port).request()
    assert wl is not None
    _, status = DataClient("127.0.0.1", farm.dataserver_port).fetch(2, 0, 0)
    assert status is FetchStatus.NOT_AVAILABLE


def test_lease_expiry_then_stale_rejected_and_regrant():
    """Full redistribution flow over virtual time through the real servers."""
    import tempfile

    from distributedmandelbrot_tpu.coordinator import ManualClock

    clock = ManualClock()
    with tempfile.TemporaryDirectory() as tmp:
        with CoordinatorHarness(tmp, [LevelSetting(1, 16)],
                                lease_timeout=10.0, clock=clock) as farm:
            client = DistributerClient("127.0.0.1", farm.distributer_port)
            w1 = client.request()
            assert w1 is not None
            assert client.request() is None  # single tile, leased
            clock.advance(11.0)
            # Expired: the slow worker's result is rejected...
            assert not client.submit(w1, np.zeros(CHUNK_PIXELS, np.uint8))
            farm.scheduler.sweep()
            # ...and the tile is regranted to the next worker.
            w2 = client.request()
            assert w2 is not None and w2.key == w1.key
            assert client.submit(w2, np.zeros(CHUNK_PIXELS, np.uint8))


def test_stalled_upload_times_out_and_regrants(tmp_path):
    """A client that echoes, receives ACCEPT, then stalls mid-upload must
    lose its claim at the read deadline — the tile becomes grantable again
    long before lease expiry (VERDICT r1 item 5; reference's toggleable
    receive timeout, Distributer.cs:17)."""
    import time

    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, 16)],
                            read_timeout=0.3) as h:
        client = DistributerClient("127.0.0.1", h.distributer_port)
        w = client.request()
        assert w is not None
        assert client.request() is None  # sole tile is leased
        with raw_conn(h.distributer_port) as s:
            s.sendall(b"\x01" + w.to_wire())
            assert framing.recv_byte(s) == 0x20
            # ... and never send the payload.
            regrant = None
            deadline = time.monotonic() + 10.0
            while regrant is None and time.monotonic() < deadline:
                time.sleep(0.05)
                regrant = client.request()
        assert regrant is not None
        assert (regrant.level, regrant.index_real, regrant.index_imag) == \
            (w.level, w.index_real, w.index_imag)
        assert h.coordinator.counters.get("read_timeouts") >= 1
        assert h.coordinator.counters.get("results_dropped") >= 1


def test_servers_survive_malformed_batch_clients(tmp_path):
    """Hostile clients on the batch extension opcodes (0x02/0x03): huge
    counts, zero counts, truncated batch frames, claim-less echoes, and
    mid-payload disconnects must not take down the accept loop or wedge
    scheduler state — after lease expiry (a hostile client's absurd-count
    lease grab holds real leases, by design) a well-behaved batch client
    still drains the farm."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(2, 64)],
                            lease_timeout=1.0, sweep_period=30.0) as farm:
        _malformed_batch_attack_rounds(farm)


def _malformed_batch_attack_rounds(farm) -> None:
    attacks = [
        b"\x02",                              # batch request, no count
        b"\x02" + struct.pack("<I", 0),       # batch request, count 0
        b"\x02" + struct.pack("<I", 2**32 - 1),  # absurd count (clamped)
        b"\x03",                              # batch response, no count
        b"\x03" + struct.pack("<I", 3),       # count, then nothing
        # count 1, then a truncated workload echo
        b"\x03" + struct.pack("<I", 1) + b"\x00" * 7,
        # count 1, never-leased workload echo (rejected, not fatal)
        b"\x03" + struct.pack("<I", 1)
        + Workload(2, 64, 1, 1).to_wire(),
    ]
    for payload in attacks:
        with raw_conn(farm.distributer_port) as s:
            s.sendall(payload)
            s.settimeout(2)
            try:
                s.recv(64)
            except (socket.timeout, ConnectionError, OSError):
                pass

    # A leased-then-abandoned batch from a hostile client must not leave
    # permanently claimed tiles: disconnect mid-upload after ACCEPT.
    with raw_conn(farm.distributer_port) as s:
        s.sendall(b"\x02" + struct.pack("<I", 1))
        assert framing.recv_byte(s) == proto.WORKLOAD_AVAILABLE
        n = struct.unpack("<I", framing.recv_exact(s, 4))[0]
        leased = [Workload.from_wire(framing.recv_exact(s, 16))
                  for _ in range(n)]
        s.sendall(b"\x03" + struct.pack("<I", 1) + leased[0].to_wire())
        # server replies per-item accept; then we vanish mid-payload
        framing.recv_byte(s)
        s.sendall(b"\x00" * 1024)  # a fraction of the 16 MiB payload

    # The hostile clients' grabbed leases release at expiry (the 1 s
    # lease above; lazy expiry makes the sweep call optional) — then a
    # legitimate batch client must be able to drain the whole farm.
    import time
    time.sleep(1.2)
    farm.scheduler.sweep()
    deadline = time.monotonic() + 15
    client = DistributerClient("127.0.0.1", farm.distributer_port)
    done = 0
    while done < 4 and time.monotonic() < deadline:
        grants = client.request_batch(4)
        if not grants:
            time.sleep(0.3)
            farm.scheduler.sweep()
            continue
        results = [(w, np.zeros(CHUNK_PIXELS, np.uint8))
                   for w in grants]
        done += sum(client.submit_batch(results))
    assert done == 4, f"farm wedged after batch attacks ({done}/4)"
    farm.wait_saves_settled(expected_accepted=4)
