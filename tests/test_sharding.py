"""Sharded-compute tests on the virtual 8-device CPU mesh."""

import jax
import numpy as np
import pytest

from distributedmandelbrot_tpu.core import LevelSetting, TileSpec, Workload
from distributedmandelbrot_tpu.ops import escape_time
from distributedmandelbrot_tpu.ops import reference as ref
from distributedmandelbrot_tpu.parallel import (MeshBackend, ROW_AXIS,
                                                batched_escape_pixels,
                                                compute_tile_row_sharded,
                                                tile_mesh, tile_row_mesh)
from distributedmandelbrot_tpu.worker import JaxBackend

DEF = 64


@pytest.fixture(scope="module")
def mesh8():
    assert jax.device_count() >= 8, "conftest should provide 8 CPU devices"
    return tile_mesh(8)


def batch_params(workloads, definition=DEF):
    params = np.empty((len(workloads), 3))
    mrds = np.empty(len(workloads), dtype=np.int64)
    for i, w in enumerate(workloads):
        spec = TileSpec.for_chunk(w.level, w.index_real, w.index_imag,
                                  definition=definition)
        params[i] = (spec.start_real, spec.start_imag,
                     spec.range_real / (definition - 1))
        mrds[i] = w.max_iter
    return params, mrds


def assert_tiles_equalish(got, want, frac=0.02):
    """Different XLA compilations may make different FMA-contraction choices
    (including in the `start + i*step` grid coordinates, a 1-ulp shift that
    moves ~1% of pixels across iteration buckets on boundary-dense tiles),
    so two compiles of the same math are not bitwise comparable.  A 2%
    budget still catches every sharding-mechanics bug — wrong tile order,
    wrong row offsets, wrong per-tile max_iter all produce ~100% mismatch."""
    got, want = np.asarray(got), np.asarray(want)
    mism = float((got != want).mean())
    assert mism <= frac, f"{mism:.2%} of pixels differ (budget {frac:.0%})"


def golden_like_device_grid(w, max_iter, definition=DEF):
    """Reference pixels computed on the device-grid coordinates (start +
    i*step in float32) so the comparison isolates the sharding, not grid
    generation."""
    spec = TileSpec.for_chunk(w.level, w.index_real, w.index_imag,
                              definition=definition)
    step = np.float32(spec.range_real / (definition - 1))
    idx = np.arange(definition, dtype=np.float32)
    cr = (np.float32(spec.start_real) + idx * step)[None, :].repeat(
        definition, 0).astype(np.float64)
    ci = (np.float32(spec.start_imag) + idx * step)[:, None].repeat(
        definition, 1).astype(np.float64)
    # f32 kernel -> compare against f32 single-device kernel instead of f64
    counts = np.asarray(escape_time.escape_counts(
        cr.astype(np.float32), ci.astype(np.float32), max_iter=max_iter))
    return np.asarray(escape_time.scale_counts_to_uint8(
        counts, max_iter=max_iter))


def test_batched_sharded_matches_single_device(mesh8):
    """8 tiles over 8 devices == the same tiles one-by-one on one device."""
    workloads = [Workload(4, 100, i % 4, i // 4) for i in range(8)]
    params, mrds = batch_params(workloads)
    got = batched_escape_pixels(mesh8, params, mrds, definition=DEF)
    assert got.shape == (8, DEF, DEF)
    for i, w in enumerate(workloads):
        assert_tiles_equalish(got[i], golden_like_device_grid(w, 100))


def test_batched_handles_non_divisible_batch(mesh8):
    """Batch of 5 on 8 devices: padded internally, unpadded on return."""
    workloads = [Workload(3, 50, i % 3, i // 3) for i in range(5)]
    params, mrds = batch_params(workloads)
    got = batched_escape_pixels(mesh8, params, mrds, definition=DEF)
    assert got.shape == (5, DEF, DEF)
    assert_tiles_equalish(got[4], golden_like_device_grid(workloads[4], 50))


def test_batched_mixed_max_iter_per_tile(mesh8):
    """Tiles from different levels carry different budgets; each must be
    cut at its own max_iter exactly as if computed alone."""
    workloads = [Workload(2, 30, 0, 0), Workload(4, 120, 1, 2)]
    params, mrds = batch_params(workloads)
    got = batched_escape_pixels(mesh8, params, mrds, definition=DEF)
    for i, w in enumerate(workloads):
        assert_tiles_equalish(got[i],
                              golden_like_device_grid(w, w.max_iter))


def test_row_sharded_tile_matches_unsharded(mesh8):
    spec = TileSpec(-0.8, 0.1, 0.2, 0.2, width=DEF, height=DEF)
    mesh = tile_row_mesh(1, 8)
    got = compute_tile_row_sharded(mesh, spec, 200)
    assert got.shape == (DEF, DEF)
    step = np.float32(spec.range_real / (DEF - 1))
    idx = np.arange(DEF, dtype=np.float32)
    cr = np.float32(spec.start_real) + idx[None, :] * step
    ci = np.float32(spec.start_imag) + idx[:, None] * step
    counts = np.asarray(escape_time.escape_counts(
        np.broadcast_to(cr, (DEF, DEF)).astype(np.float32),
        np.broadcast_to(ci, (DEF, DEF)).astype(np.float32), max_iter=200))
    expect = np.asarray(escape_time.scale_counts_to_uint8(counts,
                                                          max_iter=200))
    assert_tiles_equalish(got, expect)


def test_row_sharded_rejects_indivisible_height():
    mesh = tile_row_mesh(1, 8)
    with pytest.raises(ValueError):
        compute_tile_row_sharded(mesh, TileSpec(0, 0, 1, 1, width=60,
                                                height=60), 10)


def test_mesh_backend_end_to_end(mesh8):
    """MeshBackend fulfills the ComputeBackend contract over the mesh."""
    backend = MeshBackend(definition=DEF, mesh=mesh8)
    workloads = [Workload(4, 64, i, j) for i in range(2) for j in range(2)]
    out = backend.compute_batch(workloads)
    assert len(out) == 4
    for pixels, w in zip(out, workloads):
        assert pixels.shape == (DEF * DEF,)
        assert pixels.dtype == np.uint8
        assert_tiles_equalish(pixels, golden_like_device_grid(w, 64).ravel())
    assert backend.compute_batch([]) == []
