"""Multibrot / Burning Ship family tests: golden parity, shortcut
output-identity, tile plumbing, CLI rendering."""

import numpy as np
import pytest

from distributedmandelbrot_tpu.core import TileSpec
from distributedmandelbrot_tpu.ops import (compute_tile_family,
                                           escape_counts_family)
from distributedmandelbrot_tpu.ops import reference as ref

# Views straddling each family's set: multibrot-3 is symmetric about the
# origin; the burning ship's main body sits near the negative real axis.
MULTIBROT_VIEW = TileSpec(-1.2, -1.2, 2.4, 2.4, width=96, height=96)
SHIP_VIEW = TileSpec(-2.2, -1.2, 2.4, 2.4, width=96, height=96)


@pytest.mark.parametrize("power,burning,spec,tol", [
    # Multibrot: same FMA-only tolerance as the core f64 kernel.
    (3, False, MULTIBROT_VIEW, 5e-4),
    (5, False, MULTIBROT_VIEW, 5e-4),
    # Burning Ship: |.| folds the plane, so a last-ulp FMA difference can
    # land an orbit on the other side of a fold and diverge the
    # trajectory outright — a wider statistical band.  The select-free
    # protocol itself is EXACT: a pure-numpy mirror of the JAX loop
    # matches the frozen golden bit-for-bit (verified; the divergence is
    # entirely XLA FMA contraction).
    (2, True, SHIP_VIEW, 3e-2),
])
def test_family_f64_near_identical_to_golden(power, burning, spec, tol):
    cr, ci = spec.grid_2d()
    golden = ref.escape_counts_family(cr, ci, 300, power=power,
                                      burning=burning)
    got = np.asarray(escape_counts_family(cr, ci, max_iter=300, power=power,
                                          burning=burning))
    mismatched = got != golden
    assert mismatched.mean() <= tol, (
        f"{mismatched.mean():.2%} of pixels diverge (FMA tolerance {tol})")
    if mismatched.any():
        # Both paths must agree through a substantial prefix before any
        # chaotic divergence: the smaller (nonzero) escape count on a
        # mismatched pixel is the depth the trajectories tracked to.
        g = np.where(golden > 0, golden, np.iinfo(np.int32).max)
        w = np.where(got > 0, got, np.iinfo(np.int32).max)
        assert np.minimum(g, w)[mismatched].min() >= 50


def test_family_power2_matches_mandelbrot_golden():
    """Degree-2 non-burning multibrot IS the Mandelbrot set; pin against
    the core golden."""
    spec = TileSpec(-2.0, -2.0, 4.0, 4.0, width=64, height=64)
    cr, ci = spec.grid_2d()
    golden = ref.escape_counts(cr, ci, 200)
    got = np.asarray(escape_counts_family(cr, ci, max_iter=200, power=2))
    mism = (got != golden).mean()
    assert mism <= 5e-4


@pytest.mark.parametrize("power", [3, 4, 7])
def test_multibrot_interior_disk_pixels_never_escape(power):
    """Every pixel inside the inscribed disk must be one the golden finds
    never escapes (the disk is a strict subset of the period-1
    component), and the disk must be maximal enough to contain 0's
    neighborhood."""
    from distributedmandelbrot_tpu.ops.escape_time import (
        multibrot_interior, multibrot_interior_radius)
    spec = TileSpec(-0.8, -0.8, 1.6, 1.6, width=128, height=128)
    cr, ci = spec.grid_2d()
    mask = np.asarray(multibrot_interior(cr.astype(np.float32),
                                         ci.astype(np.float32), power))
    assert mask.any()
    golden = ref.escape_counts_family(cr, ci, 2000, power=power)
    assert (golden[mask] == 0).all()
    # d=2 must reproduce the known 1/4 value.
    assert abs(multibrot_interior_radius(2) - 0.25) < 1e-15


def test_family_cycle_check_is_output_identical():
    import jax.numpy as jnp
    for power, burning, spec in [(3, False, MULTIBROT_VIEW),
                                 (2, True, SHIP_VIEW)]:
        cr, ci = spec.grid_2d()
        cr = jnp.asarray(cr, jnp.float32)
        ci = jnp.asarray(ci, jnp.float32)
        base = np.asarray(escape_counts_family(
            cr, ci, max_iter=400, power=power, burning=burning,
            cycle_check=False))
        cyc = np.asarray(escape_counts_family(
            cr, ci, max_iter=400, power=power, burning=burning,
            cycle_check=True))
        np.testing.assert_array_equal(base, cyc)
        assert (cyc == 0).sum() > 0  # the view does contain in-set pixels


def test_family_tile_end_to_end_uint8():
    pixels = compute_tile_family(MULTIBROT_VIEW, 200, power=3,
                                 dtype=np.float64)
    assert pixels.shape == (96 * 96,) and pixels.dtype == np.uint8
    cr, ci = MULTIBROT_VIEW.grid_2d()
    golden = ref.scale_counts_to_uint8(
        ref.escape_counts_family(cr, ci, 200, power=3), 200).ravel()
    assert (pixels != golden).mean() <= 5e-4


def test_family_validation():
    cr = np.zeros((4, 4))
    with pytest.raises(ValueError, match="degree"):
        escape_counts_family(cr, cr, max_iter=10, power=1)
    with pytest.raises(ValueError, match="degree 2"):
        escape_counts_family(cr, cr, max_iter=10, power=3, burning=True)


def test_render_multibrot_and_ship(tmp_path):
    from distributedmandelbrot_tpu import cli
    for extra, name in ([["--fractal", "multibrot", "--power", "4",
                          "--center", "0,0"], "m4.png"],
                        [["--fractal", "ship", "--center", "-0.5,-0.5"],
                         "ship.png"]):
        out = str(tmp_path / name)
        rc = cli.main(["render", *extra, "--definition", "64",
                       "--max-iter", "64", "--span", "3", "--out", out])
        assert rc == 0
        import os
        assert os.path.getsize(out) > 0


def test_render_family_rejects_unsupported_combos(tmp_path):
    from distributedmandelbrot_tpu import cli
    out = str(tmp_path / "x.png")
    for argv in (
        ["render", "--fractal", "ship", "--deep", "--out", out],
        # no perturbation path: sub-threshold spans would alias float64
        ["render", "--fractal", "ship", "--span", "1e-14", "--out", out],
        ["render", "--fractal", "multibrot", "--power", "1", "--out", out],
        ["render", "--fractal", "ship", "--power", "4", "--out", out],
        ["render", "--power", "3", "--out", out],  # mandelbrot + --power
    ):
        with pytest.raises(SystemExit):
            cli.main(argv)


def test_family_smooth_classification_and_bands():
    """Smooth family values: in-set classification tracks the integer
    kernel, and escaped values are band-free (fractional parts present)
    with the degree-d renormalization keeping nu near the integer count."""
    from distributedmandelbrot_tpu.ops import escape_smooth_family
    import jax.numpy as jnp
    for power, burning, spec in [(3, False, MULTIBROT_VIEW),
                                 (2, True, SHIP_VIEW)]:
        cr, ci = spec.grid_2d()
        nu = np.asarray(escape_smooth_family(
            jnp.asarray(cr, jnp.float32), jnp.asarray(ci, jnp.float32),
            max_iter=300, power=power, burning=burning))
        counts = np.asarray(escape_counts_family(
            jnp.asarray(cr, jnp.float32), jnp.asarray(ci, jnp.float32),
            max_iter=300, power=power, burning=burning))
        agree = ((nu == 0) == (counts == 0)).mean()
        assert agree >= 0.995, f"in-set classification diverges: {agree}"
        esc = (nu > 0) & (counts > 0)
        # nu tracks the integer count within a small offset (the radius-2
        # -> bailout tail is degree-dependent, so the offset grows with
        # d; what matters is that it stays bounded)...
        assert np.abs(nu[esc] - counts[esc]).max() < 8.0
        # ...and is genuinely continuous (not integer-quantized).
        frac = nu[esc] % 1.0
        assert ((frac > 0.05) & (frac < 0.95)).mean() > 0.5


def test_animate_family_frames(tmp_path):
    from distributedmandelbrot_tpu import cli
    out_dir = str(tmp_path / "frames")
    rc = cli.main(["animate", "--fractal", "ship", "--center", "-1.75,-0.03",
                   "--span-start", "1.0", "--span-end", "0.5",
                   "--frames", "2", "--definition", "32",
                   "--max-iter", "40", "--out-dir", out_dir])
    assert rc == 0
    import os
    assert sorted(os.listdir(out_dir)) == ["frame_0000.png",
                                           "frame_0001.png"]
    with pytest.raises(SystemExit):  # no perturbation path for families
        cli.main(["animate", "--fractal", "ship", "--center", "-1.75,-0.03",
                  "--span-end", "1e-14", "--out-dir", out_dir])
    with pytest.raises(SystemExit):  # zoom-OUT starting sub-threshold
        cli.main(["animate", "--fractal", "ship", "--center", "-1.75,-0.03",
                  "--span-start", "1e-14", "--span-end", "1.0",
                  "--out-dir", out_dir])


@pytest.mark.parametrize("power", [9, 17])
def test_family_smooth_high_power_f32_no_overflow(power):
    """power >= 8 freezes lanes at |z|^2 beyond float32 max (and >= 17
    leaves NaN components via inf - inf in the frozen z); the mag2
    sanitization must keep escaped pixels finite and escaped (nu > 0)."""
    from distributedmandelbrot_tpu.ops import escape_smooth_family
    import jax.numpy as jnp
    spec = TileSpec(-1.1, -1.1, 2.2, 2.2, width=64, height=64)
    cr, ci = spec.grid_2d()
    nu = np.asarray(escape_smooth_family(
        jnp.asarray(cr, jnp.float32), jnp.asarray(ci, jnp.float32),
        max_iter=100, power=power))
    counts = np.asarray(escape_counts_family(
        jnp.asarray(cr, jnp.float32), jnp.asarray(ci, jnp.float32),
        max_iter=100, power=power))
    assert np.isfinite(nu).all()
    esc = counts > 0
    assert esc.any()
    assert (nu[esc] > 0).all(), "escaped pixels must not classify in-set"


def test_render_family_smooth(tmp_path):
    from distributedmandelbrot_tpu import cli
    out = str(tmp_path / "ship_smooth.png")
    rc = cli.main(["render", "--fractal", "ship", "--smooth",
                   "--center", "-0.5,-0.5", "--definition", "64",
                   "--max-iter", "100", "--span", "3", "--out", out])
    assert rc == 0
    import os
    assert os.path.getsize(out) > 0
