"""Unit tests for ``analysis/callgraph.py`` — the v2 engine layer.

Covers exactly what the module's docstring promises to resolve
(``self.m``, ``self.attr.m`` via inferred attribute types, module
functions, project imports, ``ClassName()`` -> ``__init__``, lexical
inheritance) and, just as deliberately, what it must leave unresolved:
callbacks, ``getattr``, duplicate class names with no disambiguating
import.  Reachability must terminate on cycles and report caller paths.
"""

from __future__ import annotations

from distributedmandelbrot_tpu.analysis import Project
from distributedmandelbrot_tpu.analysis.callgraph import graph_for

P = "distributedmandelbrot_tpu"


def graph_of(sources: dict[str, str]):
    return graph_for(Project.from_sources(sources))


def callees_of(graph, qual: str) -> list:
    return [site.callee for site in graph.calls.get(qual, [])]


# -- resolution ------------------------------------------------------------

def test_resolves_self_method_and_module_function():
    g = graph_of({f"{P}/worker/a.py": '''
def helper():
    pass

class A:
    def top(self):
        self.step()
        helper()

    def step(self):
        pass
'''})
    assert callees_of(g, f"{P}/worker/a.py::A.top") == [
        f"{P}/worker/a.py::A.step", f"{P}/worker/a.py::helper"]


def test_resolves_attr_method_via_init_annotation_and_construction():
    g = graph_of({f"{P}/worker/b.py": '''
class Sched:
    def next(self):
        pass

class Store:
    def put(self):
        pass

class Owner:
    def __init__(self, sched: Sched):
        self.sched = sched
        self.store = Store()

    def run(self):
        self.sched.next()
        self.store.put()
'''})
    assert callees_of(g, f"{P}/worker/b.py::Owner.run") == [
        f"{P}/worker/b.py::Sched.next", f"{P}/worker/b.py::Store.put"]


def test_resolves_imports_symbol_module_alias_and_constructor():
    util = f"{P}/net/util.py"
    user = f"{P}/worker/c.py"
    g = graph_of({
        util: '''
def read_u32(sock):
    pass

class Codec:
    def __init__(self):
        pass
''',
        user: f'''
from {P}.net import util
from {P}.net.util import read_u32, Codec

def direct(sock):
    read_u32(sock)

def via_module(sock):
    util.read_u32(sock)

def construct():
    return Codec()
'''})
    assert callees_of(g, f"{user}::direct") == [f"{util}::read_u32"]
    assert callees_of(g, f"{user}::via_module") == [f"{util}::read_u32"]
    assert callees_of(g, f"{user}::construct") == [f"{util}::Codec.__init__"]


def test_resolves_inherited_method_through_lexical_base():
    g = graph_of({f"{P}/serve/d.py": '''
class Base:
    def common(self):
        pass

class Child(Base):
    def run(self):
        self.common()
'''})
    assert callees_of(g, f"{P}/serve/d.py::Child.run") == [
        f"{P}/serve/d.py::Base.common"]


# -- conservatism ----------------------------------------------------------

def test_unresolvable_calls_stay_none():
    g = graph_of({f"{P}/worker/e.py": '''
import json

class E:
    def run(self, cb):
        cb()
        getattr(self, "dynamic")()
        json.dumps({})
'''})
    # getattr(...)() is two call sites (the getattr and the result).
    assert callees_of(g, f"{P}/worker/e.py::E.run") == [None] * 4


def test_duplicate_class_names_without_import_stay_unresolved():
    # Worker in two modules, neither imported here: picking one would be
    # a guess, and the rules must treat a guess as unknown.
    g = graph_of({
        f"{P}/worker/w1.py": "class Worker:\n    def go(self):\n        pass\n",
        f"{P}/serve/w2.py": "class Worker:\n    def go(self):\n        pass\n",
        f"{P}/obs/user.py": '''
class U:
    def __init__(self, w: "Worker"):
        self.w = w

    def run(self):
        self.w.go()
''',
    })
    assert callees_of(g, f"{P}/obs/user.py::U.run") == [None]


def test_nested_defs_not_walked_as_enclosing_function():
    g = graph_of({f"{P}/worker/f.py": '''
def target():
    pass

def outer():
    def later():
        target()
    return later
'''})
    # outer() itself never calls target; the nested body runs later.
    assert callees_of(g, f"{P}/worker/f.py::outer") == []
    assert callees_of(g, f"{P}/worker/f.py::outer.<locals>.later") == []


# -- reachability ----------------------------------------------------------

def test_reachable_reports_paths_and_terminates_on_cycles():
    g = graph_of({f"{P}/worker/g.py": '''
class G:
    def a(self):
        self.b()

    def b(self):
        self.c()

    def c(self):
        self.a()
'''})
    a = f"{P}/worker/g.py::G.a"
    b = f"{P}/worker/g.py::G.b"
    c = f"{P}/worker/g.py::G.c"
    reached = g.reachable(a)
    assert set(reached) == {b, c}
    # The path is the caller chain, nearest-first, excluding the target.
    assert reached[b] == (a,)
    assert reached[c] == (a, b)


def test_graph_is_cached_per_project():
    project = Project.from_sources({f"{P}/worker/h.py": "def f():\n    pass\n"})
    assert graph_for(project) is graph_for(project)
