"""Perturbation deep-zoom tests.

The capability this adds over the reference (whose only deep-zoom path is
direct float64, ``DistributedMandelbrotWorkerCUDA.py:39``): TPU-speed
f32 delta orbits against a host-side fixed-point bigint reference orbit,
valid at zooms far below float64's ~1e-16 pixel-pitch floor.
"""

import numpy as np
import pytest

from distributedmandelbrot_tpu.ops import escape_time
from distributedmandelbrot_tpu.ops import perturbation as P
from distributedmandelbrot_tpu.ops import reference as ref

# Misiurewicz-point neighborhood: boundary-rich at every depth (the
# BASELINE config-4 view).
M_RE, M_IM = "-0.77568377", "0.13646737"


def exact_count(spec, r, c, max_iter):
    bits = P.DEFAULT_PREC_BITS
    ca = P._to_fixed(spec.center_re, bits)
    cb = P._to_fixed(spec.center_im, bits)
    d_re = float((c - (spec.width - 1) / 2) * spec.step)
    d_im = float((r - (spec.height - 1) / 2) * spec.step)
    return P._escape_count_fixed(ca + P._to_fixed(d_re, bits),
                                 cb + P._to_fixed(d_im, bits),
                                 max_iter, bits)


def test_to_fixed_round_trip():
    bits = 96
    for s in ("0.5", "-1.75", "0.1", "-0.77568377", "1e-20", "-2.5e-3", "3"):
        v = P._to_fixed(s, bits)
        assert abs(P._fixed_to_float(v, bits) - float(s)) <= 2.0 ** -90
    # floats convert exactly
    for f in (0.5, -1.75, 0.1, 3.0 / 7.0):
        assert P._fixed_to_float(P._to_fixed(f, bits), bits) == f


def test_exact_counts_match_numpy_golden():
    for c in (-0.5 + 0.1j, 0.3 + 0.5j, -1.8 + 0.05j, 2.5 + 0j, -0.1 + 0j):
        want = int(ref.escape_counts(np.array([[c.real]]),
                                     np.array([[c.imag]]), 200)[0, 0])
        got = P.escape_counts_exact(repr(c.real), repr(c.imag), 200)
        assert got == want, c


def test_reference_orbit_matches_f64_iteration():
    zr, zi, n = P.reference_orbit("-0.5", "0.1", 60)
    assert n == 60  # -0.5+0.1i never escapes
    z = c = -0.5 + 0.1j
    for k in range(60):
        assert abs(zr[k] - z.real) < 1e-15 and abs(zi[k] - z.imag) < 1e-15
        z = z * z + c


def test_perturb_matches_direct_f64_at_moderate_zoom():
    spec = P.DeepTileSpec("-0.74529", "0.11307", 1e-5, width=96, height=96)
    counts, n_fixed = P.compute_counts_perturb(spec, 1500)
    step = spec.step
    col = (np.arange(96) - 47.5) * step + float(spec.center_re)
    row = (np.arange(96) - 47.5) * step + float(spec.center_im)
    want = np.asarray(escape_time.escape_counts(
        np.broadcast_to(col, (96, 96)).astype(np.float64),
        np.broadcast_to(row[:, None], (96, 96)).astype(np.float64),
        max_iter=1500))
    mism = float((counts != want).mean())
    # Both sides carry ulp-level noise at the chaotic boundary; parity is
    # statistical (sampled-exact comparison below is the strong check).
    assert mism <= 0.01, f"{mism:.2%} vs direct f64"
    assert n_fixed < 96 * 96 * 0.05


@pytest.mark.parametrize("span,max_iter,dtype", [
    (1e-10, 3000, np.float32), (1e-18, 4000, np.float32),
    (1e-50, 4000, np.float64)])  # below the 1e-30 f32 delta floor
def test_perturb_sampled_exact(span, max_iter, dtype):
    """Spot-check against exact fixed point — works beyond f64's floor
    (1e-50 exercises the auto-widened orbit precision via f64 deltas;
    the window is a single escape band at that budget, and its count
    must be EXACT)."""
    spec = P.DeepTileSpec(M_RE, M_IM, span, width=64, height=64)
    counts, _ = P.compute_counts_perturb(spec, max_iter, dtype=dtype)
    rng = np.random.default_rng(1)
    bad = 0
    for _ in range(12):
        r = int(rng.integers(64))
        c = int(rng.integers(64))
        if counts[r, c] != exact_count(spec, r, c, max_iter):
            bad += 1
    assert bad <= 1, f"{bad}/12 sampled pixels disagree with exact"


def test_perturb_escaping_center_auto_reference():
    """A view whose center escapes early must still render correctly via
    the auto-selected reference (round-1 failure mode of naive
    perturbation)."""
    # Center just outside the set: escapes fast, but the tile spans
    # boundary structure.
    spec = P.DeepTileSpec("-0.7453", "0.1127", 2e-4, width=64, height=64)
    counts, n_fixed = P.compute_counts_perturb(spec, 800)
    assert len(np.unique(counts)) > 10  # real structure, not garbage
    for r, c in ((0, 0), (31, 31), (63, 63), (10, 50)):
        assert counts[r, c] == exact_count(spec, r, c, 800), (r, c)


def test_perturb_uint8_tile_and_scaling():
    spec = P.DeepTileSpec("-0.74529", "0.11307", 1e-6, width=64, height=64)
    pixels = P.compute_tile_perturb(spec, 300)
    assert pixels.shape == (64 * 64,)
    assert pixels.dtype == np.uint8
    counts, _ = P.compute_counts_perturb(spec, 300)
    want = np.asarray(escape_time.scale_counts_to_uint8(
        counts.ravel(), max_iter=300))
    np.testing.assert_array_equal(pixels, want)


def test_perturb_trivial_budget():
    spec = P.DeepTileSpec("0", "0", 1e-3, width=32, height=32)
    counts, n_fixed = P.compute_counts_perturb(spec, 1)
    assert (counts == 0).all() and n_fixed == 0


def test_smooth_perturb_matches_escape_smooth():
    """Smooth perturbation vs the direct f64 smooth kernel: identical
    in-set mask, ~1e-13 relative error on escape values."""
    spec = P.DeepTileSpec("-0.74529", "0.11307", 1e-5, width=64, height=64)
    nu, n_fixed = P.compute_smooth_perturb(spec, 1000, dtype=np.float64)
    step = spec.step
    col = (np.arange(64) - 31.5) * step + float(spec.center_re)
    row = (np.arange(64) - 31.5) * step + float(spec.center_im)
    want = np.asarray(escape_time.escape_smooth(
        np.broadcast_to(col, (64, 64)).astype(np.float64),
        np.broadcast_to(row[:, None], (64, 64)).astype(np.float64),
        max_iter=1000))
    assert ((nu == 0) == (want == 0)).all()
    both = (nu > 0) & (want > 0)
    relerr = np.abs(nu[both] - want[both]) / np.maximum(want[both], 1)
    # Glitch-fixed pixels carry integer counts (documented banding);
    # exclude them via the count and bound the rest tightly.
    assert np.median(relerr) < 1e-9
    assert (relerr < 1e-6).mean() > 1 - (n_fixed + 1) / both.sum() - 0.01


def test_smooth_perturb_deep_fractional():
    """Past the reference orbit's own escape, the diverging-extension
    entries let escaped pixels reach the smoothing radius — nu must be
    fractional, not integer-clamped."""
    spec = P.DeepTileSpec(M_RE, M_IM, 1e-18, width=32, height=32)
    nu, _ = P.compute_smooth_perturb(spec, 4000)
    escaped = nu[nu > 0]
    assert len(escaped)
    assert not np.allclose(escaped, np.round(escaped))


def test_julia_perturb_sampled_exact():
    """Julia-family perturbation (no dc term, fixed c): sampled against
    exact fixed point at a repelling fixed point of c (on the Julia set
    at every depth), beyond f64's floor."""
    C = ("-0.8", "0.156")
    spec = P.DeepTileSpec("1.5275031186435346", "-0.07591217835228786",
                          1e-16, width=48, height=48)
    counts, _ = P.compute_counts_perturb(spec, 1500, julia_c=C)
    bits = 256
    za = P._to_fixed(spec.center_re, bits)
    zb = P._to_fixed(spec.center_im, bits)
    ca = P._to_fixed(C[0], bits)
    cb = P._to_fixed(C[1], bits)
    rng = np.random.default_rng(4)
    bad = 0
    for _ in range(10):
        r = int(rng.integers(48))
        c = int(rng.integers(48))
        d_re = float((c - 23.5) * spec.step)
        d_im = float((r - 23.5) * spec.step)
        want = P._escape_count_fixed(za + P._to_fixed(d_re, bits),
                                     zb + P._to_fixed(d_im, bits),
                                     1500, bits, ca=ca, cb=cb)
        if counts[r, c] != want:
            bad += 1
    assert bad <= 1, f"{bad}/10 disagree with exact"


def test_julia_perturb_matches_direct_at_boundary():
    C = ("-0.8", "0.156")
    spec = P.DeepTileSpec("1.5275031186435346", "-0.07591217835228786",
                          1e-5, width=64, height=64)
    counts, n_fixed = P.compute_counts_perturb(spec, 800, julia_c=C)
    step = spec.step
    col = (np.arange(64) - 31.5) * step + float(spec.center_re)
    row = (np.arange(64) - 31.5) * step + float(spec.center_im)
    want = np.asarray(escape_time.escape_counts_julia(
        np.broadcast_to(col, (64, 64)).astype(np.float64),
        np.broadcast_to(row[:, None], (64, 64)).astype(np.float64),
        complex(-0.8, 0.156), max_iter=800))
    assert float((counts != want).mean()) <= 0.02
    assert len(np.unique(counts)) > 10


def test_segmented_scan_is_output_identical_to_full_scan():
    """The early-exit segmented driver must match a pure lax.scan
    bit-for-bit (stickiness argument: once no lane is live every further
    step is a no-op), across segment sizes that divide the orbit, leave
    ragged tails, or exceed it entirely — driven with the real delta
    step on a window of fast sky, deep pixels, and glitch candidates."""
    import jax.numpy as jnp
    from jax import lax

    from distributedmandelbrot_tpu.ops import perturbation as pt

    z_re, z_im, valid = pt.reference_orbit("-0.7436447", "0.1318252", 1500)
    zr = jnp.asarray(z_re[:valid])
    zi = jnp.asarray(z_im[:valid])
    spec = pt.DeepTileSpec("-0.7436447", "0.1318252", 1e-4,
                           width=48, height=48)
    dre, dim = spec.delta_grids(np.float64)
    dre, dim = jnp.asarray(dre), jnp.asarray(dim)

    four = jnp.asarray(4.0, jnp.float64)
    tol = jnp.asarray(pt.GLITCH_TOL, jnp.float64)

    def step(carry, zs):
        # The real integer delta step (mirrors _perturb_scan.step).
        dzr, dzi, active, n, glitched = carry
        zrk, zik = zs
        fr, fi = zrk + dzr, zik + dzi
        mag2 = fr * fr + fi * fi
        zmag2 = zrk * zrk + zik * zik
        glitched = glitched | (active & (mag2 < tol * zmag2))
        active = active & (mag2 < four)
        n = n + active.astype(jnp.int32)
        ndzr = (zrk + zrk) * dzr - (zik + zik) * dzi \
            + (dzr * dzr - dzi * dzi) + dre
        ndzi = (zrk + zrk) * dzi + (zik + zik) * dzr \
            + 2 * dzr * dzi + dim
        return (ndzr, ndzi, active, n, glitched), None

    init = (dre, dim, jnp.ones(dre.shape, jnp.bool_),
            jnp.zeros(dre.shape, jnp.int32),
            jnp.zeros(dre.shape, jnp.bool_))
    want, _ = lax.scan(step, init, (zr, zi))
    for segment in (64, 100, len(z_re[:valid]), 10_000):
        got = pt._segmented_orbit_scan(step, init, zr, zi,
                                       lambda c: jnp.any(c[2]),
                                       segment=segment)
        for g, w in zip(got[2:], want[2:]):  # active, n, glitched
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_segmented_scan_actually_exits_early():
    """The while_loop must actually stop at the first all-dead segment:
    compare against a driver whose live signal is pinned True (early
    exit disabled).  With every lane escaping within a few steps of a
    50k-entry orbit, the real driver must be dramatically cheaper — a
    wall-clock ratio with a wide margin, since outputs alone cannot
    distinguish a working exit from a dead one (all later segments are
    semantic no-ops)."""
    import time

    import jax.numpy as jnp

    from distributedmandelbrot_tpu.ops import perturbation as pt

    # In-set center (the origin): its orbit covers the FULL budget, so
    # the dead-signal variant really runs all ~50k steps.
    z_re, z_im, valid = pt.reference_orbit("0", "0", 50_000)
    assert valid == 50_000
    zr, zi = jnp.asarray(z_re[:valid]), jnp.asarray(z_im[:valid])
    spec = pt.DeepTileSpec("0", "0", 1e-4, width=32, height=32)
    dre, dim = spec.delta_grids(np.float64)
    # Far-exterior deltas: every lane escapes almost immediately.
    dre, dim = jnp.asarray(dre + 3.0), jnp.asarray(dim)

    four = jnp.asarray(4.0, jnp.float64)

    def step(carry, zs):
        dzr, dzi, active, n = carry
        zrk, zik = zs
        fr, fi = zrk + dzr, zik + dzi
        active = active & (fr * fr + fi * fi < four)
        n = n + active.astype(jnp.int32)
        ndzr = (zrk + zrk) * dzr - (zik + zik) * dzi \
            + (dzr * dzr - dzi * dzi) + dre
        ndzi = (zrk + zrk) * dzi + (zik + zik) * dzr \
            + 2 * dzr * dzi + dim
        return (ndzr, ndzi, active, n), None

    init = (dre, dim, jnp.ones(dre.shape, jnp.bool_),
            jnp.zeros(dre.shape, jnp.int32))

    import jax

    def timed(live_of):
        # jit so the timed call is pure execution: eager lax control
        # flow re-traces per call, which would swamp both variants.
        run = jax.jit(lambda: pt._segmented_orbit_scan(step, init, zr, zi,
                                                       live_of))
        np.asarray(run()[3])  # compile + warmup
        t0 = time.perf_counter()
        out = run()
        np.asarray(out[3])
        return time.perf_counter() - t0, out

    t_real, real = timed(lambda c: jnp.any(c[2]))
    t_dead, dead = timed(lambda c: jnp.asarray(True))
    np.testing.assert_array_equal(np.asarray(real[3]), np.asarray(dead[3]))
    assert np.asarray(real[3]).max() <= 4  # immediate escapes
    # ~50k steps vs ~1 segment: demand only a wide, flake-proof margin.
    assert t_dead > 3 * t_real, (t_dead, t_real)


def test_second_reference_pass_fixes_glitches_exactly():
    """The Misiurewicz config-4 window: every pixel's count must equal
    the exact fixed-point value regardless of which repair machinery
    ran.  (Round 4's depth-gradient reference deepening now finds a
    reference covering nearly the whole all-exterior window, so the
    flagged set collapsed from hundreds to ~0 at this size — the
    deepening must not COST exactness; repair-path engagement itself is
    covered by test_all_exterior_glitch_cluster_repairs_exactly and
    test_stagnation_stop_flags_stragglers_output_exact.)"""
    from decimal import Decimal

    from distributedmandelbrot_tpu.ops import perturbation as pt

    cre, cim = "-0.77568376995", "0.13646737005"
    n = 48
    spec = pt.DeepTileSpec(cre, cim, 1e-10, width=n, height=n)
    counts, n_flagged = pt.compute_counts_perturb(spec, 50_000,
                                                  dtype=np.float32)
    assert n_flagged < 100  # the deepened reference covers the window
    c = np.asarray(counts)
    # The flagged set isn't returned; spot-check the densest rows around
    # the Misiurewicz point (where the glitches live) against exact
    # fixed-point, plus random pixels for the non-glitched bulk.
    import random
    rng = random.Random(9)
    step = Decimal(1e-10) / (n - 1)
    checks = [(n // 2, n // 2), (n // 2 + 1, n // 2)] + \
        [(rng.randrange(n), rng.randrange(n)) for _ in range(4)]
    for r, col in checks:
        dre = Decimal(cre) + (Decimal(col) - Decimal(n - 1) / 2) * step
        dim = Decimal(cim) + (Decimal(r) - Decimal(n - 1) / 2) * step
        want = pt.escape_counts_exact(str(dre), str(dim), 50_000)
        assert int(c[r, col]) == want, (r, col, int(c[r, col]), want)


def test_all_exterior_glitch_cluster_repairs_exactly(monkeypatch):
    """Seahorse-valley deep window (the bench headline center at span
    1e-10): its glitch cluster is ALL-exterior — every secondary-
    reference candidate's orbit escapes early, so the device repair
    pass must NOT engage (scan repairs against a truncated or exterior
    reference are not reliably exact here — measured: a truncated-
    prefix repair left 3294 vs 3247 exact, and even an f64 rescan
    mis-repaired 1 of 8).  Every flagged pixel takes the exact
    fixed-point loop and must equal infinite-precision truth."""
    flagged = {}
    orig_cand = P._secondary_candidates
    def spy_cand(bad, scanned, height, width):
        flagged["bad"] = bad.copy()
        return orig_cand(bad, scanned, height, width)
    monkeypatch.setattr(P, "_secondary_candidates", spy_cand)
    orbit_lens = []
    orig_orbit = P._orbit_fixed.__wrapped__
    def spy_orbit(*a, **k):
        r = orig_orbit(*a, **k)
        orbit_lens.append(r[2])
        return r
    monkeypatch.setattr(P, "_orbit_fixed", spy_orbit)

    cre = "-0.743643887037158704752191506114774"
    cim = "0.131825904205311970493132056385139"
    n = 48
    spec = P.DeepTileSpec(cre, cim, 1e-10, width=n, height=n)
    counts, n_flagged = P.compute_counts_perturb(spec, 50_000,
                                                 dtype=np.float32)
    # The scenario holds: a real glitch cluster whose candidates (every
    # orbit after the full-budget primary) all escape early.
    assert n_flagged > 4
    assert max(orbit_lens[1:]) < 50_000
    # Exactness: every flagged pixel equals fixed-point truth.
    c = np.asarray(counts)
    bad = flagged["bad"]
    assert len(bad) == n_flagged
    for r, col in bad[:: max(1, len(bad) // 6)]:
        want = exact_count(spec, r, col, 50_000)
        assert int(c[r, col]) == want, (r, col, int(c[r, col]), want)


def test_deep_frame_mass_glitch_fraction_cap_and_exact_batch(monkeypatch):
    """Frame-3 regime of a 1e-8 -> 1e-16 seahorse zoom (span ~1.6e-13,
    budget 20000): a large FRACTION of the tile legitimately ends up
    doubly-glitched (every secondary candidate exterior).  The old flat
    4096-pixel cap killed the render at 256^2; the cap now scales with
    the tile and the remainder goes through the (native-batched) exact
    loop — and the FLAGGED pixels stay exact.  (Unflagged pixels are
    statistically accurate f32 scan values, as everywhere else.)"""
    flagged = {}
    orig_cand = P._secondary_candidates
    def spy_cand(bad, scanned, height, width):
        flagged["bad"] = bad.copy()
        return orig_cand(bad, scanned, height, width)
    monkeypatch.setattr(P, "_secondary_candidates", spy_cand)

    cre = "-0.743643887037158704752191506114774"
    cim = "0.131825904205311970493132056385139"
    n = 48
    spec = P.DeepTileSpec(cre, cim, 1.6e-13, width=n, height=n)
    counts, n_flagged = P.compute_counts_perturb(spec, 20_000,
                                                 dtype=np.float32)
    assert n_flagged > n  # a mass-glitch view, not a few strays
    c = np.asarray(counts)
    assert (c > 0).all()  # every pixel escapes at this span/budget
    # Exactness of the flagged set (the repair contract).
    bad = flagged["bad"]
    assert len(bad) > n
    for r, col in bad[:: max(1, len(bad) // 6)]:
        want = exact_count(spec, r, col, 20_000)
        assert int(c[r, col]) == want, (r, col, int(c[r, col]), want)
    # An explicit cap is still enforced.
    with pytest.raises(ValueError, match="doubly-glitched"):
        P.compute_counts_perturb(spec, 20_000, dtype=np.float32,
                                 max_glitch_fix=3)


def test_giant_budget_orbits_use_the_small_cache():
    """Budgets past ORBIT_CACHE_MAX_STEPS must not enter the 64-deep
    LRU (budget-proportional arrays would hold gigabytes) but still
    keep a 2-deep cache — an animation reuses its center's orbit across
    frames even on the pure-Python fallback path."""
    P._orbit_cached.cache_clear()
    P._orbit_cached_giant.cache_clear()
    za = P._to_fixed("-0.5", 128)
    zb = P._to_fixed("0.1", 128)
    big = P.ORBIT_CACHE_MAX_STEPS + 1
    r1 = P._orbit_fixed(za, zb, za, zb, big, 128)
    assert P._orbit_cached.cache_info().currsize == 0
    assert P._orbit_cached_giant.cache_info().currsize == 1
    assert P._orbit_fixed(za, zb, za, zb, big, 128)[0] is r1[0]
    r2 = P._orbit_fixed(za, zb, za, zb, 500, 128)
    assert P._orbit_cached.cache_info().currsize == 1
    assert P._orbit_fixed(za, zb, za, zb, 500, 128)[0] is r2[0]


def test_device_orbit_cache_reuses_and_guards():
    """_device_orbit returns the SAME device arrays for a repeated host
    orbit (the upload dominated deep-zoom wall time on tunneled rigs)
    and re-uploads when the identity key is stale (id reuse after an
    upstream lru eviction — simulated by mutating the fingerprint)."""
    import numpy as np

    from distributedmandelbrot_tpu.ops import perturbation as pt

    pt._DEVICE_ORBIT_CACHE.clear()
    z_re = np.linspace(0.0, 1.0, 64)
    z_im = np.linspace(1.0, 2.0, 64)
    a1, b1 = pt._device_orbit(z_re, z_im)
    a2, b2 = pt._device_orbit(z_re, z_im)
    assert a1 is a2 and b1 is b2  # cache hit: no re-upload
    assert np.allclose(np.asarray(a1), z_re.astype(np.asarray(a1).dtype))

    # Same ids, different content (the id-reuse hazard): fingerprint
    # mismatch must force a fresh upload, not serve the stale arrays.
    z_re[-1] = 123.0
    a3, _ = pt._device_orbit(z_re, z_im)
    assert a3 is not a1
    assert float(np.asarray(a3)[-1]) == 123.0
    pt._DEVICE_ORBIT_CACHE.clear()


def test_bla_matches_exact_scan_on_filament_view():
    """The BLA fast path (opt-in) agrees with the exact scan to its
    documented contract: >= 99% pixel agreement on a boundary-crossing
    view, and EXACT agreement where no skip ever rides over an escape
    (the c=i Misiurewicz filaments at a budget deep enough to skip)."""
    spec = P.DeepTileSpec("0", "1", 1e-12, width=64, height=64)
    exact, _ = P.compute_counts_perturb(spec, 3000)
    fast, _ = P.compute_counts_perturb(spec, 3000, bla=True)
    agree = float((exact == fast).mean())
    assert agree >= 0.99, f"BLA agreement {agree:.4f}"
    # Escaped/in-set CLASSIFICATION must agree everywhere the counts do
    # not: late detection shifts a count, never flips in-set status for
    # lanes that took exact steps near their escape.
    assert (((exact == 0) == (fast == 0)).mean()) >= 0.99


def test_bla_skips_cover_inset_budget():
    """An all-interior deep window (the period-6 bond point of the main
    cardioid: c = 3/8 + i*sqrt(3)/8, exact to arbitrary digits) must
    classify every pixel in-set through the full budget under BLA —
    skipping may never turn a bounded orbit into an escape."""
    from distributedmandelbrot_tpu.ops.bla import (BOND_POINT_IM,
                                                    BOND_POINT_RE)

    spec = P.DeepTileSpec(BOND_POINT_RE, BOND_POINT_IM, 1e-15,
                          width=32, height=32)
    exact, _ = P.compute_counts_perturb(spec, 4000)
    fast, _ = P.compute_counts_perturb(spec, 4000, bla=True)
    assert np.array_equal(exact, fast)
    assert (exact == 0).all()


def test_bla_table_composition():
    """The first STORED level's coefficients equal the exact composition
    of the BLA_MIN_SKIP single-step linearizations they merge
    (dz' = A dz + B dc with the quadratic terms dropped)."""
    from distributedmandelbrot_tpu.ops.bla import (BLA_MIN_SKIP,
                                                    build_bla_table)

    rng = np.random.default_rng(7)
    n = 2 * BLA_MIN_SKIP
    # Bounded-orbit-like values keep the composition well-conditioned.
    z = 0.5 * (rng.normal(size=n) + 1j * rng.normal(size=n))
    A_re, A_im, B_re, B_im, R2 = build_bla_table(
        z.real.copy(), z.imag.copy(), dc_max=1e-12)
    dz = 1e-10 + 0j
    dc = 1e-12 + 0j
    want = dz
    for k in range(BLA_MIN_SKIP):
        want = 2.0 * z[k] * want + dc
    got = (A_re[0, 0] + 1j * A_im[0, 0]) * dz \
        + (B_re[0, 0] + 1j * B_im[0, 0]) * dc
    assert abs(got - want) <= 1e-6 * max(abs(want), 1e-30)
    assert (R2 >= 0).all() and np.isfinite(R2).all()


def test_bla_smooth_matches_exact_on_inset_view():
    """Smooth BLA: bit-identical nu on the all-interior bond-point view
    (every pixel classifies in-set, no freeze to approximate), and the
    freeze-exactness guard — on a mixed view every BLA pixel whose nu
    differs from the exact scan differs by a small count shift, never a
    corrupted smoothing fraction (|dnu| bounded by the max skip)."""
    from distributedmandelbrot_tpu.ops.bla import (BOND_POINT_IM,
                                                    BOND_POINT_RE)

    spec = P.DeepTileSpec(BOND_POINT_RE, BOND_POINT_IM, 1e-15,
                          width=32, height=32)
    exact, _ = P.compute_smooth_perturb(spec, 4000)
    fast, _ = P.compute_smooth_perturb(spec, 4000, bla=True)
    assert np.array_equal(exact, fast)
    assert (exact == 0).all()

    spec2 = P.DeepTileSpec("0", "1", 1e-12, width=48, height=48)
    e, _ = P.compute_smooth_perturb(spec2, 3000)
    f, _ = P.compute_smooth_perturb(spec2, 3000, bla=True)
    # In-set classification must agree, and the TYPICAL escaped pixel's
    # nu must be exact-scan quality: the z_cap guard keeps freezes in
    # exact bursts, so deviations come only from the eps-perturbed delta
    # trajectory (measured p99 ~0.1 of one band on boundary views) plus
    # rare whole-skip count shifts — a corrupted smoothing fraction
    # would blow the percentile bound immediately.
    assert (((e == 0) == (f == 0)).mean()) >= 0.99
    both = (e != 0) & (f != 0)
    diff = np.abs(e[both] - f[both])
    assert np.percentile(diff, 95) <= 1.0, float(np.percentile(diff, 95))


def test_bla_julia_mode():
    """BLA in Julia mode (add_dc=False — the skip's B term rides a zero
    dc): classification agreement with the exact scan on the deep Julia
    view the exact-parity test uses."""
    C = ("-0.8", "0.156")
    spec = P.DeepTileSpec("1.5275031186435346", "-0.07591217835228786",
                          1e-16, width=48, height=48)
    exact, _ = P.compute_counts_perturb(spec, 1500, julia_c=C)
    fast, _ = P.compute_counts_perturb(spec, 1500, julia_c=C, bla=True)
    assert (((exact == 0) == (fast == 0)).mean()) >= 0.99
    assert float((exact == fast).mean()) >= 0.99


def test_bla_escape_straddling_segments_never_selectable():
    """Regression (review finding): a reference orbit escaping near the
    budget produces merge segments straddling the escape whose
    coefficients saturate to inf in f32; with a positive radius, a
    zero-delta lane skipped through one NaN-poisons into a false
    in-set.  The builder must zero every such entry's radius, and an
    exterior-center render whose orbit covers the budget must classify
    its pixels escaped, identically to the exact scan."""
    from distributedmandelbrot_tpu.ops.bla import (BLA_MIN_SKIP,
                                                    build_bla_table)

    # Exterior point just past the cardioid cusp: escape count ~150
    # (must exceed BLA_MIN_SKIP so the table actually stores levels and
    # a stored segment straddles the escape — with a shorter orbit this
    # test would be vacuous); budget just above the escape so the +12
    # orbit extension still covers it (the case where the
    # orbit_len < max_iter glitch flag can NOT catch the bug).
    c = 0.2504
    z = 0j
    orbit = []
    e = None
    for k in range(1, 400):
        z = z * z + c
        orbit.append(z)
        if e is None and abs(z) >= 2:
            e = k
            # true diverging extension, like _orbit_fixed's
            for _ in range(12):
                z = z * z + c
                if abs(z) > 1e50:
                    break
                orbit.append(z)
            break
    orbit = np.array(orbit)
    from distributedmandelbrot_tpu.ops.bla import BLA_MIN_SKIP as MS
    assert e is not None and e > 2 * MS, f"test premise broken: e={e}"
    A_re, A_im, B_re, B_im, R2 = build_bla_table(
        orbit.real.copy(), orbit.imag.copy(), dc_max=1e-13)
    assert (R2 > 0).any(), "test premise broken: no stored level valid"
    f32_max = float(np.finfo(np.float32).max)
    huge = ((np.abs(A_re) >= f32_max) | (np.abs(A_im) >= f32_max)
            | (np.abs(B_re) >= f32_max) | (np.abs(B_im) >= f32_max))
    assert not (huge & (R2 > 0)).any(), \
        "saturating coefficients with selectable radius"
    # Segments containing a post-escape |Z| >= 4 entry are invalid:
    # the first such entry appears within 2 steps of the escape.
    first_bad = (e + 1) // BLA_MIN_SKIP
    assert (R2[0, first_bad:] == 0).all()

    # End-to-end: exterior center, budget = escape + 3 <= orbit cover.
    spec = P.DeepTileSpec("0.2504", "0", 1e-13, width=16, height=16)
    exact, _ = P.compute_counts_perturb(spec, e + 3)
    fast, _ = P.compute_counts_perturb(spec, e + 3, bla=True)
    assert np.array_equal(exact, fast)
    assert (exact != 0).all()  # every pixel escaped — none falsely in-set


import jax.numpy as jnp  # noqa: E402 (deep-path tests below)


def test_pack_mask_roundtrip():
    """Device-side bit-packing of the glitch mask inverts exactly on the
    host for every size class (the fetch-trim path of round 4)."""
    import jax

    rng = np.random.RandomState(7)
    for n in (1, 7, 8, 64, 1000, 4096):
        g = rng.rand(n) < 0.3
        packed = np.asarray(jax.jit(P._pack_mask)(jnp.asarray(g)))
        assert packed.dtype == np.uint8
        assert (P._unpack_mask_np(packed, g.shape) == g).all()


def test_fetch_trim_is_lossless():
    """The trimmed fetch (uint16 counts + packed mask) equals the raw
    scan exactly — same inputs, widened on the host."""
    mi = 300
    zr = jnp.asarray(np.full(mi, 0.1))
    zi = jnp.asarray(np.zeros(mi))
    rng = np.random.RandomState(3)
    dre = jnp.asarray(rng.uniform(-2, 2, (8, 16)).astype(np.float32))
    dim = jnp.asarray(rng.uniform(-2, 2, (8, 16)).astype(np.float32))
    counts, glitched, _ = P._perturb_scan(zr, zi, dre, dim, max_iter=mi)
    v, packed = P._perturb_scan_fetch(zr, zi, dre, dim, max_iter=mi)
    assert np.asarray(v).dtype == np.uint16
    assert (np.asarray(v).astype(np.int32) == np.asarray(counts)).all()
    assert (P._unpack_mask_np(np.asarray(packed), dre.shape)
            == np.asarray(glitched)).all()


def test_stagnation_stop_flags_stragglers_output_exact():
    """A mixed view with a few bounded pixels (a minibrot sliver) that
    would otherwise drag the scan through the whole budget: the
    stagnation stop hands them to the exact repair and the final counts
    still match the fixed-point golden pixel-for-pixel (the repair is
    exact, so the stop is output-invariant)."""
    side, mi = 16, 20000
    # Window around the period-3 minibrot sized so ~46 of 256 pixels are
    # in-set — below the stagnation cap (64), so once boundary escapes
    # cease the stop must fire and flag exactly those stragglers.
    c_re, c_im = "-1.7548776662466927", "0.0"
    span = 5e-2
    spec = P.DeepTileSpec(c_re, c_im, span, width=side, height=side)
    counts, ng = P.compute_counts_perturb(spec, mi)
    assert (counts == 0).any(), "premise: view must contain in-set pixels"
    assert ng > 0
    # Fixed-point golden for EVERY pixel — exact by construction.
    bits = 192
    za = P._to_fixed(c_re, bits)
    zb = P._to_fixed(c_im, bits)
    step = spec.step
    pts = []
    for r in range(side):
        for c in range(side):
            d_re = float((c - (side - 1) / 2) * step)
            d_im = float((r - (side - 1) / 2) * step)
            pts.append((za + P._to_fixed(d_re, bits),
                        zb + P._to_fixed(d_im, bits)))
    golden = P._escape_counts_exact_batch(pts, mi, bits, None)
    assert (counts.reshape(-1) == golden).all()


def test_segmented_scan_stagnation_driver():
    """Driver-level stagnation semantics: a small live set whose count
    stops changing exits after the quiet window with those lanes marked
    suspect; a live set above the cap runs to the end, suspect empty."""
    steps = 4096
    zr = jnp.asarray(np.zeros(steps))
    zi = jnp.asarray(np.zeros(steps))

    def step(carry, zs):
        alive, n = carry
        return (alive, n + alive.astype(jnp.int32)), None

    for n_live, cap, expect_stop in ((4, 16, True), (64, 16, False)):
        alive0 = jnp.asarray(np.arange(128) < n_live)
        (alive, n), suspect = P._segmented_orbit_scan(
            step, (alive0, jnp.zeros(128, jnp.int32)), zr, zi,
            lambda c: jnp.any(c[0]),
            stagnation=(lambda c: jnp.sum(c[0], dtype=jnp.int32),
                        lambda c: c[0], cap))
        n = np.asarray(n)
        if expect_stop:
            assert np.asarray(suspect).sum() == n_live
            assert n.max() < steps  # stopped before the orbit end
        else:
            assert not np.asarray(suspect).any()
            assert n.max() == steps


def test_auto_bla_probe_decisions(caplog):
    """The bla=None auto-probe enables BLA on the slow-dynamics bond
    view and declines on an early-escaping-reference view (config-4
    class), with the decision logged and cached."""
    import logging

    from distributedmandelbrot_tpu.ops.bla import (BOND_POINT_IM,
                                                   BOND_POINT_RE)

    mi = P.BLA_AUTO_MIN_BUDGET
    bond = P.DeepTileSpec(BOND_POINT_RE, BOND_POINT_IM, 1e-15,
                          width=16, height=16)
    P._AUTO_BLA_CACHE.clear()
    with caplog.at_level(logging.INFO, logger="distributedmandelbrot_tpu"):
        counts_auto, _ = P.compute_counts_perturb(bond, mi)
    assert any("BLA auto-enabled" in r.message for r in caplog.records)
    counts_bla, _ = P.compute_counts_perturb(bond, mi, bla=True)
    assert (counts_auto == counts_bla).all()

    # Early-escaping reference (exterior-dominated view): auto declines
    # without even probing (orbit shorter than the budget).
    caplog.clear()
    mis = P.DeepTileSpec("-0.77568376995", "0.13646737005", 1e-10,
                         width=16, height=16)
    with caplog.at_level(logging.INFO, logger="distributedmandelbrot_tpu"):
        counts_m, _ = P.compute_counts_perturb(mis, mi)
    assert not any("BLA auto-enabled" in r.message for r in caplog.records)
    exact_m, _ = P.compute_counts_perturb(mis, mi, bla=False)
    assert (counts_m == exact_m).all()


def test_smooth_bla_exact_on_boundary_view():
    """SMOOTH_Z_CAP guard (round 4): on the config-4 boundary view the
    smooth BLA path must equal the exact smooth scan bit-for-bit — at
    the integer path's 4.0 cap it differed on 17.7% of pixels with
    outliers up to 72 bands (measured on hardware; the guard note in
    ops/bla.py carries the full sweep)."""
    spec = P.DeepTileSpec("-0.77568376995", "0.13646737005", 1e-10,
                          width=64, height=64)
    mi = 30000
    nu_e, _ = P.compute_smooth_perturb(spec, mi, bla=False)
    nu_b, _ = P.compute_smooth_perturb(spec, mi, bla=True)
    assert (np.asarray(nu_e) == np.asarray(nu_b)).all()
