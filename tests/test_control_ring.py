"""Unit tests for the consistent-hash ring (control/ring.py).

The contract under test is the one the sharded control plane leans on:
ownership is a pure function of ``(n_shards, replicas)`` — endpoints
and ring version can be rewritten without remapping a single key — and
the per-shard durable namespace depends only on the slice identity.
All jax-free.
"""

import pytest

from distributedmandelbrot_tpu.control.ring import (
    DEFAULT_REPLICAS, HashRing, RingConfigError, ShardInfo,
    load_ring_for_shard, parse_shard_spec, shard_namespace)


def _grid(level):
    return [(level, i, j) for i in range(level) for j in range(level)]


def test_ownership_ignores_endpoints_and_version():
    # Endpoints and version are the *rewritable* part of the config (a
    # restarted shard comes back on fresh ephemeral ports); ownership
    # must not notice.
    local = HashRing.local(4)
    real = HashRing(
        [ShardInfo("10.0.0.%d" % k, distributer_port=59000 + k,
                   dataserver_port=60000 + k, gateway_port=61000 + k)
         for k in range(4)],
        version=7)
    for key in _grid(16):
        assert local.owner_of(key) == real.owner_of(key)


def test_ownership_changes_with_replicas():
    a = HashRing.local(4)
    b = HashRing.local(4, replicas=DEFAULT_REPLICAS * 2)
    assert any(a.owner_of(k) != b.owner_of(k) for k in _grid(32))


def test_every_shard_owns_part_of_the_grid():
    ring = HashRing.local(4)
    owners = {ring.owner_of(k) for k in _grid(16)}
    assert owners == {0, 1, 2, 3}


def test_owner_and_owner_of_agree_and_stay_in_range():
    ring = HashRing.local(3)
    for key in _grid(8):
        owner = ring.owner_of(key)
        assert owner == ring.owner(*key)
        assert 0 <= owner < ring.n_shards


def test_config_round_trip(tmp_path):
    path = str(tmp_path / "ring.json")
    ring = HashRing(
        [ShardInfo("127.0.0.1", distributer_port=59010,
                   dataserver_port=59011),
         ShardInfo("127.0.0.2", distributer_port=59020, gateway_port=59022)],
        version=3, replicas=32)
    ring.save(path)
    loaded = HashRing.load(path)
    assert loaded.version == 3
    assert loaded.replicas == 32
    assert loaded.shards == ring.shards
    for key in _grid(8):
        assert loaded.owner_of(key) == ring.owner_of(key)


def test_load_rejects_garbage(tmp_path):
    path = tmp_path / "ring.json"
    with pytest.raises(RingConfigError):
        HashRing.load(str(path))  # no such file
    path.write_text("not json", encoding="utf-8")
    with pytest.raises(RingConfigError):
        HashRing.load(str(path))
    path.write_text('{"format": 99, "shards": []}', encoding="utf-8")
    with pytest.raises(RingConfigError):
        HashRing.load(str(path))


def test_from_config_validation():
    with pytest.raises(RingConfigError):
        HashRing.from_config([])  # not an object
    with pytest.raises(RingConfigError):
        HashRing.from_config({"format": 1, "shards": []})
    with pytest.raises(RingConfigError):
        HashRing.from_config(
            {"format": 1, "shards": [{"host": "x"}]})  # missing port


def test_ctor_validation():
    with pytest.raises(RingConfigError):
        HashRing([])
    with pytest.raises(RingConfigError):
        HashRing.local(2, replicas=0)
    with pytest.raises(RingConfigError):
        HashRing.local(2, version=0)


def test_slice_partition_and_namespace():
    ring = HashRing.local(3, version=5)
    slices = [ring.slice(k) for k in range(3)]
    for key in _grid(8):
        owning = [s for s in slices if s.owns(key)]
        assert len(owning) == 1
        assert owning[0].shard == ring.owner_of(key)
        assert owning[0].owner_of(key) == ring.owner_of(key)
    for s in slices:
        assert s.n_shards == 3
        assert s.version == 5
        # The namespace is the durable identity: slice only, never the
        # version — a version bump must not orphan on-disk state.
        assert s.namespace == f"-s{s.shard}of3"
        assert s.namespace == shard_namespace(s.shard, 3)
    with pytest.raises(RingConfigError):
        ring.slice(3)
    with pytest.raises(RingConfigError):
        ring.slice(-1)


def test_parse_shard_spec():
    assert parse_shard_spec("0/1") == (0, 1)
    assert parse_shard_spec("3/4") == (3, 4)
    for bad in ("", "2", "a/b", "1.5/4", "4/4", "-1/4", "0/0"):
        with pytest.raises(RingConfigError):
            parse_shard_spec(bad)


def test_load_ring_for_shard(tmp_path):
    path = str(tmp_path / "ring.json")
    HashRing.local(2, version=4).save(path)
    sl = load_ring_for_shard(path, 1, 2)
    assert (sl.shard, sl.n_shards, sl.version) == (1, 2, 4)
    # Mismatched launch would silently re-partition the keyspace.
    with pytest.raises(RingConfigError):
        load_ring_for_shard(path, 0, 3)
    # Without a file, K/N alone determines ownership.
    sl = load_ring_for_shard(None, 2, 4)
    assert (sl.shard, sl.n_shards) == (2, 4)
    assert all(sl.owns(k) == (HashRing.local(4).owner_of(k) == 2)
               for k in _grid(8))
