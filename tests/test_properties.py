"""Property-based tests (hypothesis) for the pure data-plane invariants.

The seeded-random tests elsewhere pin known shapes; these let hypothesis
hunt the edges (empty runs, 255-valued bytes, run lengths crossing the
u32 record boundary, wire values at the uint32 extremes, ASCII-filename
edge cases) for the contracts third parties depend on: codec round-trip
identity, pick-min optimality, wire/index byte-format round-trips.
"""

from __future__ import annotations

import io

import numpy as np
import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st

from distributedmandelbrot_tpu import codecs
from distributedmandelbrot_tpu.codecs import RAW, RLE
from distributedmandelbrot_tpu.core.workload import Workload
from distributedmandelbrot_tpu.storage.index import (EntryType, IndexEntry,
                                                     read_entry)

# Byte arrays: mix run-heavy (RLE-friendly) and noisy shapes.
_raw_bytes = st.binary(min_size=1, max_size=4096)
_run_heavy = st.lists(
    st.tuples(st.integers(1, 300), st.integers(0, 255)),
    min_size=1, max_size=64,
).map(lambda runs: np.repeat(
    np.array([v for _, v in runs], np.uint8),
    np.array([n for n, _ in runs])))
_arrays = st.one_of(
    _raw_bytes.map(lambda b: np.frombuffer(b, np.uint8)),
    _run_heavy)


@settings(max_examples=200, deadline=None)
@given(_arrays)
def test_codec_roundtrip_identity(data):
    payload = codecs.serialize(data)
    out = codecs.deserialize(payload, data.size)
    np.testing.assert_array_equal(out, data)


@settings(max_examples=200, deadline=None)
@given(_arrays)
def test_pick_min_is_optimal_and_sizes_are_truthful(data):
    """serialize() must pick the smallest codec, and each codec's
    encoded_size must equal its actual encoding's size (the costing that
    replaces the reference's SizeCountStream dry-run)."""
    payload = codecs.serialize(data)
    sizes = {}
    for codec in (RAW, RLE):
        body = codec.encode(data)
        assert codec.encoded_size(data) == len(body)
        sizes[codec.code] = 1 + len(body)
    assert len(payload) == min(sizes.values())


@settings(max_examples=200, deadline=None)
@given(st.integers(1, 2**32 - 1), st.integers(0, 2**32 - 1),
       st.integers(0, 2**32 - 1), st.integers(0, 2**32 - 1))
def test_workload_wire_roundtrip(level, mrd, i, j):
    """16-byte LE wire format round-trips across the full uint32 range
    (reference format: DistributerWorkload.cs:53-100)."""
    w = Workload(level, mrd, i % max(level, 1), j % max(level, 1))
    again = Workload.from_wire(w.to_wire())
    assert again == w and len(w.to_wire()) == 16


_names = st.text(
    alphabet=st.characters(min_codepoint=33, max_codepoint=126,
                           exclude_characters="/\\"),
    min_size=1, max_size=64)


@settings(max_examples=200, deadline=None)
@given(st.integers(1, 2**31 - 1), st.integers(0, 2**31 - 1),
       st.integers(0, 2**31 - 1),
       st.sampled_from(list(EntryType)), _names)
def test_index_entry_roundtrip(level, i, j, etype, name):
    """Index entries round-trip through the reference's byte format
    (int32 LE type field; ASCII filename for Regular entries only)."""
    filename = name if etype == EntryType.REGULAR else None
    entry = IndexEntry(level, i % level, j % level, etype, filename)
    buf = io.BytesIO(entry.to_bytes())
    again = read_entry(buf)
    assert again == entry
    assert buf.read() == b""  # no trailing bytes
