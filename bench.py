"""Benchmark harness: prints ONE JSON line with the headline metric.

Headline: escape-time throughput in Mpixels/s at max_iter=1000 on the
seahorse-valley zoom (BASELINE.md config 2 view), best of the two device
compute paths (Pallas block-early-exit kernel vs XLA sharded path).

Methodology — dispatch-latency amortization.  On the dev rig the TPU sits
behind a network tunnel with a ~70 ms per-dispatch round trip and
~35 MB/s device->host bandwidth, and ``block_until_ready`` returns before
remote completion; naive per-tile timing therefore measures the tunnel,
not the chip (round 1's 28.7 Mpix/s was exactly that).  Device throughput
is measured by chaining K tile kernels inside ONE jitted call that
reduces every tile to a checksum on device, so a run moves 4 bytes over
the wire and pays the round trip once, amortized over K tiles; the
result is forced with ``np.asarray`` (the only reliable completion
barrier here).  End-to-end farm numbers (sockets, persistence) are
reported separately by the farm config with real materialization.

``vs_baseline`` is measured against the driver's north star of
500 Mpix/s (BASELINE.json) — set for a TPU v2-8; single-chip runs are
reported as-is.

Usage: python bench.py [--tile 1024] [--tiles N] [--max-iter 1000]
                       [--dtype f32] [--repeats 3] [--all] [--farm]
                       [--serve] [--worst] [--tileshape] [--deep-slow]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

NORTH_STAR_MPIX_S = 500.0

# Seahorse valley: boundary-dense, iteration-heavy — a conservative view
# (full-view tiles with fast escapes bench much higher).
SEAHORSE = (-0.748, 0.09)


def _mesh_and_kernel():
    import jax

    from distributedmandelbrot_tpu.parallel import (batched_escape_pixels,
                                                    tile_mesh)
    mesh = tile_mesh()
    return jax, mesh, batched_escape_pixels


def _grid_params(center, span: float, tile: int, tiles: int) -> np.ndarray:
    """(tiles, 3) params covering a FIXED 4x4 grid of sub-windows of the
    view: batches larger than 16 cycle through the same 16 sub-windows,
    so growing the batch amortizes dispatch latency without drifting the
    view toward easier (faster-escaping) regions.  The single copy of
    the sub-window scheme — the seahorse headline and the worst-case
    configs must never diverge in methodology."""
    sub = span / 4
    x0, y0 = center[0] - span / 2, center[1] - span / 2
    params = np.empty((tiles, 3))
    for i in range(tiles):
        params[i] = (x0 + (i % 4) * sub, y0 + ((i // 4) % 4) * sub,
                     sub / (tile - 1))
    return params


def _bench_params(tile: int, tiles: int):
    # The historical seahorse window: 4x4 sub-tiles of span 0.005 corner-
    # anchored at SEAHORSE (== a 0.02 window centered half a span up-right).
    return _grid_params((SEAHORSE[0] + 0.01, SEAHORSE[1] + 0.01), 0.02,
                        tile, tiles)


def _time_chain(fn, repeats: int) -> float:
    """Median wall time of a jitted scalar-returning chain, forced with
    np.asarray (the completion barrier that works through the tunnel)."""
    np.asarray(fn())  # warmup/compile
    times = []
    for _ in range(max(repeats, 2)):
        t0 = time.perf_counter()
        np.asarray(fn())
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def _reps_chain(one_rep, params, reps: int):
    """The ONE copy of the in-jit repetition idiom behind chained-delta
    device timing: ``one_rep(params) -> int32 checksum`` is repeated
    ``reps`` times inside a single jit with a data dependency between
    repetitions — the addend is data-dependent (and numerically
    sub-ulp), so XLA can neither fold it nor CSE the repeated dispatch,
    and the checksum chain forces sequential device execution.  Used by
    ``_pallas_chain`` and the hardware tools (tools/hw_compact.py) so
    the methodology can never drift between bench rows and hardware
    artifacts.  ``params`` must be float32."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(params):
        s = one_rep(params)
        for _ in range(reps - 1):
            params = params + (s & 1).astype(jnp.float32) * 1e-12
            s = s + one_rep(params)
        return s

    return lambda: run(params)


def _pallas_chain(params_np: np.ndarray, tile: int, max_iter: int,
                  reps: int = 1, **kernel_kw):
    """One jitted call: lax.map of the Pallas kernel over K tiles,
    each reduced to a checksum on device.  ``kernel_kw`` passes static
    kernel options through (interior_check/cycle_check for raw-loop
    timing, power/burning for the extended families, interpret for the
    CPU config, block_h/block_w overrides for the tuning sweep).

    ``reps`` repeats the whole batch inside the SAME jit with a
    data dependency between repetitions (the checksum perturbs the next
    repetition's params by a symbolic term XLA cannot fold away), so
    ``time(reps=3) - time(reps=1)`` isolates pure device time from the
    per-call dispatch+sync constant (~70-75 ms on this rig — see
    ROUND4_NOTES.md "The per-call constant")."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from distributedmandelbrot_tpu.ops.pallas_escape import (
        _pallas_escape, _pallas_escape_mega, fit_blocks, DEFAULT_BLOCK_H,
        SCOUT_MIN_ITER, SCOUT_SEGMENTS_DEFAULT)

    from distributedmandelbrot_tpu.parallel.sharding import widen_square_pitch

    block_h, block_w = fit_blocks(
        tile, tile, block_h=kernel_kw.pop("block_h", DEFAULT_BLOCK_H),
        block_w=kernel_kw.pop("block_w", None))
    params = jnp.asarray(widen_square_pitch(params_np), jnp.float32)
    k = params.shape[0]

    # K > 1 rides the megakernel — the default fused dispatch route
    # (PallasBackend.dispatch_many), so the headline benches exactly
    # what production launches.  Scout default mirrors
    # compute_tiles_mega_pallas; pass scout_segments=0 for pure-f32
    # controls (the roofline's iters_exact counts f32 work only).
    scout_segments = kernel_kw.pop(
        "scout_segments",
        SCOUT_SEGMENTS_DEFAULT if max_iter >= SCOUT_MIN_ITER else 0)
    mrds = jnp.full((k, 1), max_iter, jnp.int32)

    def one_rep(params):
        if k > 1:
            out, scout = _pallas_escape_mega(
                params, mrds, k=k, height=tile, width=tile,
                max_iter=max_iter, block_h=block_h, block_w=block_w,
                scout_segments=int(scout_segments), **kernel_kw)
            # The scout census joins the checksum so the second output
            # can't be dead-code-eliminated out of the timed graph.
            return jnp.sum(out.astype(jnp.int32), dtype=jnp.int32) \
                + jnp.sum(scout, dtype=jnp.int32)

        def one(p):
            out = _pallas_escape(p[None, :], height=tile, width=tile,
                                 max_iter=max_iter, block_h=block_h,
                                 block_w=block_w, **kernel_kw)
            # dtypes pinned: under x64 a bare sum would accumulate in
            # int64, which this TPU generation does not support.
            return jnp.sum(out.astype(jnp.int32), dtype=jnp.int32)
        return jnp.sum(lax.map(one, params), dtype=jnp.int32)

    return _reps_chain(one_rep, params, reps)


def _mega_scout_share(params_np: np.ndarray, tile: int, max_iter: int,
                      **kernel_kw) -> float:
    """Untimed probe for the attribution fields: the fraction of the
    batch's pixels the bf16 scouting pass predicts escape inside its
    window (0.0 when the batch is a singleton or the scout is disarmed
    at this budget).  Advisory telemetry only — the scout never changes
    counts (the parity-guard contract in ops/mixed_precision.py)."""
    import jax.numpy as jnp

    from distributedmandelbrot_tpu.ops.pallas_escape import (
        _pallas_escape_mega, fit_blocks, DEFAULT_BLOCK_H,
        SCOUT_MIN_ITER, SCOUT_SEGMENTS_DEFAULT)

    from distributedmandelbrot_tpu.parallel.sharding import widen_square_pitch

    k = params_np.shape[0]
    scout_segments = (SCOUT_SEGMENTS_DEFAULT
                      if max_iter >= SCOUT_MIN_ITER else 0)
    if k < 2 or scout_segments == 0:
        return 0.0
    block_h, block_w = fit_blocks(
        tile, tile, block_h=kernel_kw.pop("block_h", DEFAULT_BLOCK_H),
        block_w=kernel_kw.pop("block_w", None))
    params = jnp.asarray(widen_square_pitch(params_np), jnp.float32)
    mrds = jnp.full((k, 1), max_iter, jnp.int32)
    _, scout = _pallas_escape_mega(
        params, mrds, k=k, height=tile, width=tile, max_iter=max_iter,
        block_h=block_h, block_w=block_w,
        scout_segments=scout_segments, **kernel_kw)
    return round(float(jnp.sum(scout)) / (k * tile * tile), 4)


# Measured dense-kernel ceiling of this chip, chained-delta methodology:
# the all-live compacted resume kernel at (64, 128) blocks, 2026-07-31
# (ROUND4_NOTES.md "Roofline fields").  The denominator of
# vpu_util_frac — a MEASURED ceiling, not a datasheet number, so the
# utilization fields compare kernels against what this chip has
# actually demonstrated (the round-3 audit's ~2.0 vreg-ops/cycle at
# ~12 vector ops/iteration on a ~1.7 GHz VPU predicts the same order).
PEAK_GITER_S = 520.0


def _copy_device_fields(out: dict, df: dict, prefix: str = "") -> None:
    """Propagate the latency-decomposition fields (when resolved) into a
    result row — the ONE copy of the field names, so every config's
    artifact carries identical keys."""
    if "device_mpix_s" in df:
        out[f"{prefix}device_mpix_s"] = df["device_mpix_s"]
        out[f"{prefix}call_overhead_s"] = df["call_overhead_s"]


def _device_fields(maker, pixels: int, repeats: int,
                   iters_exact: int | None = None) -> dict:
    """Latency-decomposed fields for one benched config: ``maker(reps)``
    must return a chain callable repeating the payload ``reps`` times
    in-jit (see ``_pallas_chain``).  Returns the tunnel-inclusive
    1-call wall alongside the pure device rate, their difference (the
    per-call dispatch+sync constant), and — when the payload's executed
    iteration count is exact (uniform full-budget controls) — the
    device Giter/s and its fraction of the measured chip ceiling."""
    t1 = _time_chain(maker(1), repeats)
    t3 = _time_chain(maker(3), repeats)
    dev = (t3 - t1) / 2
    out = {"benched_mpix_s": round(pixels / t1 / 1e6, 2)}
    if dev <= 0.02 * t1:
        # Timing noise ate the delta (a jittery dispatch-constant median
        # can land t3 at or below t1): flag it instead of emitting a
        # nonsense device rate into the artifact.
        out["device_unresolved"] = True
        return out
    out["device_mpix_s"] = round(pixels / dev / 1e6, 2)
    out["call_overhead_s"] = round(max(t1 - dev, 0.0), 4)
    if iters_exact is not None:
        giter = iters_exact / dev / 1e9
        out["giter_s"] = round(giter, 1)
        out["vpu_util_frac"] = round(giter / PEAK_GITER_S, 3)
    return out


def _work_integral(params_np: np.ndarray, tile: int, mi: int,
                   unroll: int, block_h: int, block_w: int
                   ) -> tuple[int, int]:
    """Exact executed vector-lane iterations of the RAW block kernel
    (shortcuts off) on this batch, from per-pixel escape counts: a block
    retires when its deepest live lane does, in ``unroll``-step segments,
    and every lane of the block rides the vector unit until then.  An
    escaped pixel's depth is its count; a never-escaped pixel runs to
    the cap (mi - 1).  Returns ``(executed, ideal)`` where ``ideal`` is
    the per-pixel depth sum — their ratio is the straggler overhead the
    block granule pays for depth spread (round-5 verdict item 3)."""
    import jax.numpy as jnp

    from distributedmandelbrot_tpu.ops.escape_time import escape_counts

    cap = mi - 1
    executed = 0
    ideal = 0
    for p in params_np:
        # The kernel's own grid convention: f32 start + index * step.
        stepv = np.float32(p[2])
        cr = (np.float32(p[0])
              + np.arange(tile, dtype=np.float32) * stepv)[None, :]
        ci = (np.float32(p[1])
              + np.arange(tile, dtype=np.float32) * stepv)[:, None]
        counts = np.asarray(escape_counts(
            jnp.broadcast_to(jnp.asarray(cr), (tile, tile)),
            jnp.broadcast_to(jnp.asarray(ci), (tile, tile)), max_iter=mi))
        depth = np.where(counts == 0, cap, counts).astype(np.int64)
        ideal += int(depth.sum())
        bmax = depth.reshape(tile // block_h, block_h,
                             tile // block_w, block_w).max(axis=(1, 3))
        segs = -(-bmax // unroll)  # ceil
        executed += int(segs.sum()) * unroll * block_h * block_w
    return executed, ideal


def _pallas_sharded_chain(mesh, params_np: np.ndarray, mrds: np.ndarray,
                          tile: int, interpret: bool | None = None):
    """The shard_map-wrapped Pallas path, reduced on device — the mesh-
    apples-to-apples twin of _xla_chain.  ``interpret`` defaults to
    auto (compiled on TPU, interpreter elsewhere) so the chain stays
    drivable on the CPU config."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributedmandelbrot_tpu.parallel.mesh import TILE_AXIS
    from distributedmandelbrot_tpu.parallel.sharding import (
        _batched_pallas_sharded, pad_to_mesh, pallas_batch_config,
        widen_square_pitch)

    # The production dispatch policy verbatim (bucketed cap, TRUE-budget
    # probe + batch-grid resolution) so this chain measures exactly what
    # sharding.batched_escape_pixels_pallas would run.
    cfg = pallas_batch_config(tile, int(mrds.max()), interpret=interpret)
    params_np, mrds = pad_to_mesh(params_np, mrds, mesh.devices.size)
    params_np = widen_square_pitch(params_np)
    sharding = NamedSharding(mesh, P(TILE_AXIS))
    params = jax.device_put(jnp.asarray(params_np, jnp.float32), sharding)
    mrd_arr = jax.device_put(jnp.asarray(mrds, jnp.int32), sharding)

    @jax.jit
    def run(params, mrd_arr):
        out = _batched_pallas_sharded(params, mrd_arr, mesh=mesh,
                                      definition=tile, clamp=False, **cfg)
        return jnp.sum(out.astype(jnp.int32), dtype=jnp.int32)

    return lambda: run(params, mrd_arr)


def _xla_chain(mesh, params_np: np.ndarray, mrds: np.ndarray, tile: int,
               segment: int, np_dtype, *, interior_check: bool = True,
               cycle_check: bool | None = None):
    """The sharded XLA path, reduced on device (same methodology)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributedmandelbrot_tpu.parallel.mesh import TILE_AXIS
    from distributedmandelbrot_tpu.parallel.sharding import (
        _batched_escape_sharded, pad_to_mesh)

    from distributedmandelbrot_tpu.ops.escape_time import INT32_SCALE_LIMIT
    cap = int(mrds.max())
    if cap - 1 >= INT32_SCALE_LIMIT:
        raise ValueError("device-chain bench is int32-only; "
                         "this max_iter needs the library path")
    # Pad tiles escape immediately, so they don't perturb the measurement.
    params_np, mrds = pad_to_mesh(params_np, mrds, mesh.devices.size)
    sharding = NamedSharding(mesh, P(TILE_AXIS))
    params = jax.device_put(jnp.asarray(params_np, np_dtype), sharding)
    mrd_arr = jax.device_put(jnp.asarray(mrds, jnp.int32), sharding)

    @jax.jit
    def run(params, mrd_arr):
        out = _batched_escape_sharded(params, mrd_arr, mesh=mesh,
                                      definition=tile, max_iter_cap=cap,
                                      segment=segment, clamp=False,
                                      cycle_check=cycle_check,
                                      interior_check=interior_check)
        return jnp.sum(out.astype(jnp.int32), dtype=jnp.int32)

    return lambda: run(params, mrd_arr)


def bench_throughput(tile: int, tiles: int, max_iter: int, dtype: str,
                     repeats: int, segment: int = 256) -> dict:
    """Fastest of the available compute paths (XLA sharded; Pallas on TPU)."""
    jax, mesh, _ = _mesh_and_kernel()
    np_dtype = {"f32": np.float32, "f64": np.float64}[dtype]
    n_dev = mesh.devices.size
    # Pad the batch to the mesh size for the sharded path.
    k = tiles + ((-tiles) % n_dev)
    params = _bench_params(tile, k)
    mrds = np.full(k, max_iter, dtype=np.int64)
    pixels = k * tile * tile

    results: dict[str, float] = {}
    extra_fields: dict = {}
    results["xla"] = pixels / _time_chain(
        _xla_chain(mesh, params, mrds, tile, segment, np_dtype), repeats) / 1e6

    if dtype == "f32":
        try:  # Pallas path: block-granular early exit; TPU only.
            from distributedmandelbrot_tpu.ops.pallas_escape import (
                pallas_available)
            if pallas_available():
                # Latency decomposition + roofline for the headline
                # (device rate via in-jit repetition delta; giter from
                # the exact-work uniform control — see bench_worstcase).
                # _device_fields' reps=1 timing IS the headline number —
                # the payload is timed once, not twice.
                df = _device_fields(
                    lambda r: _pallas_chain(params, tile, max_iter,
                                            reps=r), pixels, repeats)
                results["pallas"] = df["benched_mpix_s"]
                _copy_device_fields(extra_fields, df)
                if k > 1:
                    # Fused-launch attribution: the megakernel pays ONE
                    # dispatch constant for the K-tile batch, so the
                    # per-tile overhead is the headline's divided by K.
                    extra_fields["fusion_width"] = k
                    if "call_overhead_s" in extra_fields:
                        extra_fields["call_overhead_per_tile_s"] = round(
                            extra_fields["call_overhead_s"] / k, 6)
                    extra_fields["bf16_share"] = _mega_scout_share(
                        params, tile, max_iter)
                params_u = _grid_params(*UNIFORM_VIEW, tile, k)
                extra_fields.update(
                    {f: v for f, v in _device_fields(
                        lambda r: _pallas_chain(params_u, tile, max_iter,
                                                reps=r,
                                                interior_check=False,
                                                cycle_check=False,
                                                scout_segments=0),
                        pixels, repeats,
                        iters_exact=pixels * (max_iter - 1)).items()
                     if f in ("giter_s", "vpu_util_frac")})
        except Exception as e:  # never let an experimental path kill bench
            print(f"# pallas path skipped: {type(e).__name__}: {e}",
                  file=sys.stderr)

    try:
        # Native C++ backend: bit-exact f64 with per-pixel early exit,
        # multithreaded — the production CPU path.  Measured only off-TPU
        # (Pallas dwarfs it there and the host compute would just inflate
        # wall time); on a CPU fallback it is the honest best number (the
        # XLA-on-virtual-mesh chain measures an emulation, not a path a
        # CPU farm would run).
        from distributedmandelbrot_tpu import native as native_mod
        if (jax.default_backend() != "tpu"
                and native_mod.native_supported()):
            from distributedmandelbrot_tpu.core.geometry import TileSpec
            grids = []  # params cycles with period 16: build unique grids
            for p in params[:min(k, 16)]:
                spec = TileSpec(p[0], p[1], p[2] * (tile - 1),
                                p[2] * (tile - 1), width=tile, height=tile)
                grids.append(spec.grid_flat())

            def run_native():
                for i in range(k):
                    cr, ci = grids[i % len(grids)]
                    native_mod.escape_pixels(cr, ci, max_iter)
                return np.zeros(())

            results["native"] = pixels / _time_chain(run_native,
                                                     repeats) / 1e6
    except Exception as e:
        print(f"# native path skipped: {type(e).__name__}: {e}",
              file=sys.stderr)

    path, mpix_s = max(results.items(), key=lambda kv: kv[1])
    others = {f"{p}_mpix_s": round(v, 2) for p, v in results.items()}
    # The winning path dictates the label: the native path is host C++
    # at f64 on one machine, not the requested dtype on the JAX devices.
    if path == "native":
        how = "f64, seahorse valley, host, native path, multithreaded C++"
    else:
        how = (f"{dtype}, seahorse valley, "
               f"{n_dev} {jax.devices()[0].platform} device(s), "
               f"{path} path, device-chained")
    return {
        "metric": f"Mpixels/s @ max_iter={max_iter} "
                  f"({k}x{tile}^2 {how})",
        "value": round(mpix_s, 2),
        "unit": "Mpix/s",
        "vs_baseline": round(mpix_s / NORTH_STAR_MPIX_S, 4),
        **others,
        **extra_fields,
    }


def _mpix(pixels: int, seconds: float) -> float:
    return pixels / seconds / 1e6


# Analytic arithmetic split of one escape iteration between the two
# issue ports: of the ~12 vector ops/iteration the round-3 audit counted
# for the VPU recurrence, the complex-square multiply-accumulate chain
# (the part ops/mxu_iteration.mxu_step moves onto the matrix units as a
# 2x2 matmul) is ~6 — so full MXU mode relocates about half the
# iteration's arithmetic off the VPU.  Used only for the utilization-
# split attribution fields; the measured rates stay measured.
MXU_STEP_SHARE = 0.5


def _mxu_split_fields(df: dict) -> dict:
    """VPU/MXU utilization-split attribution for one benched row: which
    mode the ops/mxu_iteration gate resolves to on this platform, and
    where the iteration's arithmetic consequently runs.  In ``off`` and
    ``census`` modes the timed kernel's recurrence is pure VPU work (the
    census is an untimed advisory shadow), so the MXU fraction is 0; in
    ``full`` mode the matmul-form recurrence moves ``MXU_STEP_SHARE`` of
    it to the matrix units."""
    from distributedmandelbrot_tpu.ops.mxu_iteration import (
        mxu_mode, mxu_parity_proven)
    mode = mxu_mode()
    out = {"mxu_mode": mode, "mxu_parity_proven": mxu_parity_proven(),
           "mxu_step_share": MXU_STEP_SHARE}
    if "vpu_util_frac" in df:
        if mode == "full":
            out["mxu_util_frac"] = round(
                df["vpu_util_frac"] * MXU_STEP_SHARE, 3)
            out["vpu_util_frac"] = round(
                df["vpu_util_frac"] * (1.0 - MXU_STEP_SHARE), 3)
        else:
            out["mxu_util_frac"] = 0.0
    return out


def _enqueue_cost(maker, n: int = 25) -> float:
    """Host-side async-dispatch cost of one fused launch: min wall time
    to *enqueue* (not complete) the warmed jitted call.  This resolves
    the per-launch constant even where the chained-delta clamps to zero
    — on CPU rigs the whole launch constant (tens of µs) sits below the
    device-time jitter that ``t3 - t1`` has to subtract through."""
    import jax
    jax.block_until_ready(maker())
    best = None
    for _ in range(n):
        t0 = time.perf_counter()
        handle = maker()
        dt = time.perf_counter() - t0
        jax.block_until_ready(handle)
        best = dt if best is None else min(best, dt)
    return best


def bench_kernel_batch(tile: int, max_iter: int, repeats: int,
                       ks: list[int]) -> dict:
    """``--kernel-batch``: sweep the megakernel's fusion width K at the
    headline view/budget — one latency-decomposed row per K, so the
    BENCH_* trajectory can attribute the fused-dispatch win (the
    per-tile call overhead falls ~1/K while the device rate stays
    flat).  K=1 is the unfused control (per-tile kernel, no scout).
    Each row carries both overhead bases (chained-delta
    ``call_overhead_s`` and the host ``enqueue_overhead_s`` constant —
    see :func:`_enqueue_cost`) and the VPU/MXU utilization-split
    attribution (``giter_s``/``vpu_util_frac`` measured on the raw
    shortcut-free control against its exact work integral, then split
    by :func:`_mxu_split_fields`); the summary adds
    ``overhead_cut_vs_k64`` (per-tile dispatch overhead at K=64 over
    the best sweep point) when the sweep includes K=64, naming which
    basis resolved it."""
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        DEFAULT_UNROLL, fit_blocks, pallas_available)
    interp = not pallas_available()  # off-TPU: correctness-only numbers
    bh, bw = fit_blocks(tile, tile)
    rows = []
    for k in ks:
        params = _bench_params(tile, k)
        pixels = k * tile * tile
        df = _device_fields(
            lambda r, p=params: _pallas_chain(p, tile, max_iter, reps=r,
                                              interpret=interp),
            pixels, repeats)
        row = {"k": k, "fusion_width": k, **df}
        if "call_overhead_s" in df:
            row["call_overhead_per_tile_s"] = round(
                df["call_overhead_s"] / k, 6)
        enq = _enqueue_cost(
            _pallas_chain(params, tile, max_iter, reps=1,
                          interpret=interp))
        row["enqueue_overhead_s"] = round(enq, 8)
        row["enqueue_overhead_per_tile_s"] = round(enq / k, 10)
        try:
            # Utilization split from the raw shortcut-free control: its
            # executed iteration count is exactly the block-granular
            # work integral, so giter_s is a real rate, not an estimate.
            executed, _ = _work_integral(params, tile, max_iter,
                                         DEFAULT_UNROLL, bh, bw)
            row.update({f: v for f, v in _device_fields(
                lambda r, p=params: _pallas_chain(
                    p, tile, max_iter, reps=r, interpret=interp,
                    interior_check=False, cycle_check=False,
                    scout_segments=0),
                pixels, repeats, iters_exact=executed).items()
                if f in ("giter_s", "vpu_util_frac")})
        except Exception as e:  # attribution only — never kill the sweep
            print(f"# util split skipped (k={k}): "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
        row.update(_mxu_split_fields(row))
        row["bf16_share"] = _mega_scout_share(params, tile, max_iter,
                                              interpret=interp)
        rows.append(row)
    out = {"metric": f"megakernel fusion-width sweep "
                     f"({tile}^2, max_iter={max_iter}, seahorse valley)",
           "unit": "Mpix/s per row", "rows": rows}

    def _cut(table: dict) -> float | None:
        if 64 not in table or len(table) < 2 or table[64] <= 0:
            return None
        best = min(table.values())
        return round(table[64] / best, 2) if best > 0 else None

    delta_table = {r["k"]: r["call_overhead_per_tile_s"] for r in rows
                   if "call_overhead_per_tile_s" in r}
    # The chained-delta basis is only trustworthy when it shows the
    # 1/K physics (per-tile overhead non-increasing in K, within 20%).
    # A loaded or jittery host leaves residual noise in t3 - t1 that
    # can fabricate an inverted table; prefer the enqueue basis then.
    ks_sorted = sorted(delta_table)
    monotone = all(delta_table[a] >= 0.8 * delta_table[b]
                   for a, b in zip(ks_sorted, ks_sorted[1:]))
    delta_cut = _cut(delta_table) if monotone else None
    enq_cut = _cut({r["k"]: r["enqueue_overhead_per_tile_s"]
                    for r in rows})
    if delta_cut is not None:
        out["overhead_cut_vs_k64"] = delta_cut
        out["overhead_cut_basis"] = "chained-delta call overhead"
    elif enq_cut is not None:
        out["overhead_cut_vs_k64"] = enq_cut
        out["overhead_cut_basis"] = ("host enqueue constant (chained "
                                     "delta below this rig's noise "
                                     "floor)")
    return out


def _mesh_mega_chain(mesh, params_np: np.ndarray, tile: int,
                     max_iter: int, reps: int = 1,
                     interpret: bool | None = None):
    """Chained-delta timing payload for the MESH megakernel route: one
    jitted call shard_maps ``_pallas_escape_mega`` over the ``tiles``
    axis of ``mesh`` (the exact kernel the worker's mesh dispatch runs)
    and reduces pixels + scout to a checksum.  Same ``reps`` chaining as
    :func:`_pallas_chain` so ``t3 - t1`` isolates device time from the
    per-launch dispatch constant."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributedmandelbrot_tpu.ops.pallas_escape import (
        _pallas_escape_mega, fit_blocks, pallas_available,
        DEFAULT_BLOCK_H, SCOUT_MIN_ITER, SCOUT_SEGMENTS_DEFAULT)
    from distributedmandelbrot_tpu.parallel.mesh import TILE_AXIS
    from distributedmandelbrot_tpu.parallel.sharding import (
        shard_map, widen_square_pitch)

    if interpret is None:
        interpret = not pallas_available()
    n_dev = int(mesh.devices.size)
    params_np = widen_square_pitch(params_np).astype(np.float32)
    k = params_np.shape[0]
    pad = (-k) % n_dev
    if pad:
        # Same trivial-tile padding as the production mesh route: |c|>2
        # escapes on iteration 1, budget 1 — negligible padded work.
        params_np = np.concatenate(
            [params_np, np.tile(np.float32([3.0, 3.0, 0.0, 0.0]),
                                (pad, 1))])
    mrds_np = np.concatenate(
        [np.full((k, 1), max_iter, np.int32),
         np.ones((pad, 1), np.int32)])
    k_loc = (k + pad) // n_dev
    block_h, block_w = fit_blocks(tile, tile, block_h=DEFAULT_BLOCK_H)
    scout_segments = (SCOUT_SEGMENTS_DEFAULT
                      if max_iter >= SCOUT_MIN_ITER else 0)
    sharding = NamedSharding(mesh, P(TILE_AXIS))
    params = jax.device_put(jnp.asarray(params_np), sharding)
    mrd_arr = jax.device_put(jnp.asarray(mrds_np), sharding)

    shard_fn = shard_map(
        lambda p, m: _pallas_escape_mega(
            p, m, k=k_loc, height=tile, width=tile, max_iter=max_iter,
            block_h=block_h, block_w=block_w, interpret=interpret,
            scout_segments=scout_segments),
        mesh=mesh, in_specs=(P(TILE_AXIS), P(TILE_AXIS)),
        out_specs=(P(TILE_AXIS), P(TILE_AXIS)))

    def one_rep(params):
        out, scout = shard_fn(params, mrd_arr)
        return jnp.sum(out.astype(jnp.int32), dtype=jnp.int32) \
            + jnp.sum(scout, dtype=jnp.int32)

    return _reps_chain(one_rep, params, reps)


def bench_mesh(tile: int, max_iter: int, repeats: int,
               ks: list[int]) -> dict:
    """``--mesh``: devices x K scaling of the mesh megakernel worker
    route — for each local-device count (powers of two up to the ring)
    and each fusion width K, one latency-decomposed row of the
    shard_map'd fused launch, plus per-row scaling efficiency against
    the same K on one device.  A final ``worker`` row times the actual
    ``PallasBackend.dispatch_many`` + materialize path end-to-end (the
    tunnel-inclusive number a farm worker would see) at the full ring.

    On a CPU rig the "devices" are virtual XLA host devices carved from
    the host cores (``--mesh-devices`` / the 8-device fallback mesh), so
    scaling rows measure dispatch mechanics, not added silicon — on a
    1-core container expect flat-to-inverse device scaling; the rows
    exist to pin the route's overhead shape, and real scaling numbers
    must come from a multi-chip rig."""
    import jax
    from jax.sharding import Mesh

    from distributedmandelbrot_tpu.parallel.mesh import (TILE_AXIS,
                                                         device_ring)
    ring = device_ring()
    dev_counts = [n for n in (1, 2, 4, 8, 16, 32) if n <= len(ring)]
    if len(ring) not in dev_counts:
        dev_counts.append(len(ring))
    rows = []
    base_dev: dict[int, float] = {}
    for n in dev_counts:
        mesh = Mesh(np.array(ring[:n]), (TILE_AXIS,))
        for k in ks:
            params = _bench_params(tile, k)
            pixels = k * tile * tile
            df = _device_fields(
                lambda r, p=params, m=mesh: _mesh_mega_chain(
                    m, p, tile, max_iter, reps=r),
                pixels, repeats)
            row = {"devices": n, "k": k, **df}
            if "call_overhead_s" in df:
                row["call_overhead_per_tile_s"] = round(
                    df["call_overhead_s"] / k, 6)
            if "device_mpix_s" in df:
                if n == 1:
                    base_dev[k] = df["device_mpix_s"]
                if base_dev.get(k):
                    row["scaling_vs_1dev"] = round(
                        df["device_mpix_s"] / base_dev[k], 3)
            rows.append(row)
    # End-to-end worker leg: the production dispatch_many route (mesh
    # when the ring is >1 wide) with real materialization, so the row
    # carries what a farm worker would bench, not just the chained rate.
    from distributedmandelbrot_tpu.core.workload import Workload
    from distributedmandelbrot_tpu.worker.backends import PallasBackend
    k = max(ks)
    backend = PallasBackend(definition=tile)
    wls = [Workload(4, max_iter, i % 4, (i // 4) % 4) for i in range(k)]
    for h in backend.dispatch_many(wls):  # warmup/compile off the clock
        backend.materialize_tile(h)
    t0 = time.perf_counter()
    handles = backend.dispatch_many(wls)
    for h in handles:
        backend.materialize_tile(h)
    wall = time.perf_counter() - t0
    worker = {"row": "worker", "devices": backend.mesh_width, "k": k,
              "benched_mpix_s": round(k * tile * tile / wall / 1e6, 2)}
    return {"metric": f"mesh megakernel devices x K scaling "
                      f"({tile}^2, max_iter={max_iter}, seahorse valley, "
                      f"{len(ring)}-device ring)",
            "unit": "Mpix/s per row", "rows": rows, "worker": worker,
            "platform": jax.devices()[0].platform}


def _bench_numpy_fallback(tile: int, max_iter: int, ks: list[int],
                          metric: str) -> dict:
    """jax-free smoke path for the ``--kernel-batch`` / ``--mesh`` legs:
    one single-tile numpy-reference timing, scaled rows marked
    ``fallback`` so no artifact can mistake them for kernel numbers.
    Exists so CI lanes without jax can still exercise the CLI surface
    (arg parsing + JSON shape) end to end."""
    # Inline vectorized escape loop: the ops package's golden reference
    # is unreachable without jax (ops/__init__ pulls the XLA kernels),
    # and this row is a smoke rate, not a parity anchor.
    from distributedmandelbrot_tpu.core.geometry import TileSpec

    side = min(tile, 128)  # keep the smoke cheap; rate is per-pixel
    spec = TileSpec(SEAHORSE[0], SEAHORSE[1], 0.005, 0.005,
                    width=side, height=side)
    cr, ci = spec.grid_2d()
    t0 = time.perf_counter()
    c = cr + 1j * ci
    z = np.zeros_like(c)
    live = np.ones(c.shape, bool)
    for _ in range(max_iter):
        z[live] = z[live] * z[live] + c[live]
        live &= (z.real * z.real + z.imag * z.imag) < 4.0
    rate = _mpix(side * side, time.perf_counter() - t0)
    rows = [{"k": k, "benched_mpix_s": round(rate, 2),
             "fallback": "numpy"} for k in ks]
    return {"metric": metric, "unit": "Mpix/s per row", "rows": rows,
            "fallback": "numpy",
            "note": "jax unavailable: single-tile numpy reference rate; "
                    "no fusion or mesh ran"}


def bench_config1(repeats: int) -> dict:
    """BASELINE config 1: 256^2, max_iter=256, full view, CPU reference path."""
    from distributedmandelbrot_tpu.core.geometry import TileSpec
    from distributedmandelbrot_tpu.ops import reference as ref

    spec = TileSpec(-2.0, -1.25, 2.5, 2.5, width=256, height=256)
    cr, ci = spec.grid_2d()

    def run():
        ref.scale_counts_to_uint8(ref.escape_counts(cr, ci, 256), 256)
        return np.zeros(())

    v = _mpix(256 * 256, _time_chain(run, repeats))
    return {"metric": "config1 CPU-reference 256^2 mi=256 full view",
            "value": round(v, 2), "unit": "Mpix/s"}


def bench_config2(repeats: int, segment: int) -> dict:
    """BASELINE config 2: 1024^2, max_iter=1000, seahorse, one device.

    Device throughput via the K-chain; p50 tile turnaround measured on the
    materialized path (includes D2H — on this rig, the tunnel)."""
    from distributedmandelbrot_tpu.core.geometry import TileSpec
    from distributedmandelbrot_tpu.ops import compute_tile
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_pallas, pallas_available)

    k = 32
    params = _bench_params(1024, k)
    df = _device_fields(
        lambda r: _pallas_chain(params, 1024, 1000, reps=r),
        k * 1024 * 1024, repeats) if pallas_available() else None
    span = 0.005
    spec = TileSpec(SEAHORSE[0], SEAHORSE[1], span, span,
                    width=1024, height=1024)
    tile_fn = (lambda: compute_tile_pallas(spec, 1000)) \
        if pallas_available() else \
        (lambda: compute_tile(spec, 1000, segment=segment))
    tile_fn()  # warmup
    times = []
    for _ in range(max(repeats * 3, 5)):
        t0 = time.perf_counter()
        tile_fn()
        times.append(time.perf_counter() - t0)
    times.sort()
    p50 = times[len(times) // 2]
    out = {"metric": "config2 single-device 1024^2 mi=1000 seahorse",
           "value": df["benched_mpix_s"] if df else
           round(_mpix(1024 * 1024, min(times)), 2),
           "unit": "Mpix/s",
           "p50_tile_turnaround_s": round(p50, 4)}
    if df:
        _copy_device_fields(out, df)
    return out


def bench_config3(repeats: int, segment: int) -> dict:
    """BASELINE config 3: 8x1024^2 batch, max_iter=5000, mesh-sharded,
    best compute path, plus 1->N scaling efficiency."""
    jax, mesh, _ = _mesh_and_kernel()
    n = max(8, mesh.devices.size)
    params = _bench_params(1024, n)
    mrds = np.full(n, 5000, dtype=np.int64)

    t_n = _time_chain(_xla_chain(mesh, params, mrds, 1024, segment,
                                 np.float32), repeats)
    best, path = t_n, "xla"
    try:
        from distributedmandelbrot_tpu.ops.pallas_escape import (
            pallas_available)
        if pallas_available():
            t_p = _time_chain(
                _pallas_sharded_chain(mesh, params, mrds, 1024), repeats)
            if t_p < best:
                best, path = t_p, "pallas"
    except Exception as e:
        print(f"# config3 pallas path skipped: {type(e).__name__}: {e}",
              file=sys.stderr)
    out = {"metric": f"config3 {mesh.devices.size}-device {n}x1024^2 "
                     f"mi=5000 ({path} path)",
           "value": round(_mpix(n * 1024 * 1024, best), 2), "unit": "Mpix/s"}
    if path == "pallas":
        try:
            # Latency decomposition: an 8.4 Mpix dispatch is dominated
            # by the rig's per-call constant — the device rate is the
            # chip truth.  Optional fields must never kill the headline
            # row (same degrade rule as the path selection above).
            df = _device_fields(
                lambda r: _pallas_chain(params, 1024, 5000, reps=r),
                n * 1024 * 1024, repeats)
            _copy_device_fields(out, df)
        except Exception as e:
            print(f"# config3 decomposition skipped: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
        try:
            # Round-5 verdict item 3 — attribute the device rate:
            #  * raw leg (shortcuts off) has an EXACT work integral, so
            #    its Giter/s and utilization need no cost model;
            #  * straggler_work_frac names the depth-spread overhead the
            #    block granule pays (executed / ideal lane-iterations);
            #  * the cycle probe's cost is isolated by an explicit
            #    on/off A/B at this config's own budget — NOT the
            #    CYCLE_CHECK_MIN_ITER policy boundary, which at this
            #    depth class can also flip the
            #    batch-grid dispatch mode and would confound the probe
            #    with the dispatch shape.
            from distributedmandelbrot_tpu.ops.pallas_escape import (
                DEFAULT_UNROLL, fit_blocks)
            bh, bw = fit_blocks(1024, 1024)
            executed, ideal = _work_integral(params, 1024, 5000,
                                             DEFAULT_UNROLL, bh, bw)
            pixels = n * 1024 * 1024
            df_raw = _device_fields(
                lambda r: _pallas_chain(params, 1024, 5000, reps=r,
                                        interior_check=False,
                                        cycle_check=False,
                                        scout_segments=0),
                pixels, repeats, iters_exact=executed)
            _copy_device_fields(out, df_raw, prefix="raw_")
            if "giter_s" in df_raw:
                out["giter_s"] = df_raw["giter_s"]
                out["vpu_util_frac"] = df_raw["vpu_util_frac"]
            out["straggler_work_frac"] = round(executed / ideal, 3)
            df_nocc = _device_fields(
                lambda r: _pallas_chain(params, 1024, 5000, reps=r,
                                        cycle_check=False),
                pixels, repeats)
            if "device_mpix_s" in df_nocc and "device_mpix_s" in out:
                out["probe_off_device_mpix_s"] = df_nocc["device_mpix_s"]
                out["cycle_probe_cost_frac"] = round(
                    df_nocc["device_mpix_s"] / out["device_mpix_s"] - 1, 3)
        except Exception as e:
            print(f"# config3 attribution skipped: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
    if mesh.devices.size > 1:
        from distributedmandelbrot_tpu.parallel import tile_mesh
        t_1 = _time_chain(_xla_chain(tile_mesh(1), params, mrds, 1024,
                                     segment, np.float32), repeats)
        out["scaling_efficiency_1_to_n"] = round(
            t_1 / (t_n * mesh.devices.size), 3)
    return out


def bench_config4(repeats: int) -> dict:
    """BASELINE config 4: deep zoom at scale 1e-10, max_iter=50000,
    float64 + smooth coloring (128^2 probe tile)."""
    from distributedmandelbrot_tpu.core.geometry import TileSpec
    from distributedmandelbrot_tpu.ops import compute_tile_smooth

    # Misiurewicz-point neighborhood: boundary-rich at every depth.
    # 512^2 probe: the BASELINE config fixes view/budget, not tile size,
    # and production tiles are 4096^2 — at 128^2 the deep-zoom scans are
    # pure dispatch latency (16 vregs of work per orbit step) and the
    # measurement says nothing about the machine.  Measured scaling of
    # the f32 delta scan on the dev v5e: 0.19 (128^2) -> 0.70 (256^2) ->
    # 1.59 (512^2) -> 4.64 Mpix/s (1024^2); 512^2 keeps the bench
    # repeats affordable while sitting on the honest part of the curve.
    side = 512
    spec = TileSpec(-0.77568377, 0.13646737, 1e-10, 1e-10,
                    width=side, height=side)

    def run():
        return compute_tile_smooth(spec, 50000, dtype=np.float64)

    import jax
    was_x64 = jax.config.jax_enable_x64
    try:
        v = _mpix(side * side, _time_chain(run, max(1, repeats - 1)))
    finally:
        # ensure_x64 is global and sticky; later configs (and the farm)
        # must not inherit int64 promotion this TPU can't lower.
        jax.config.update("jax_enable_x64", was_x64)

    # Perturbation path: f32 delta orbits against a bigint reference —
    # the TPU-native deep-zoom answer (direct f64 emulates slowly and
    # stops near 1e-16; perturbation reaches ~1e-30 in f32).  Timing
    # includes the host-side reference orbit (re-derived per call).
    # Same view as the f64 tile above: TileSpec's coords are the CORNER,
    # DeepTileSpec's the center — corner + span/2 aligns them.
    out = {"metric": f"config4 deep-zoom 1e-10 mi=50000 "
                     f"(best of f64+smooth {side}^2 / f32 perturbation "
                     f"{side}^2 and 1024^2; the {side}^2 rate is bounded "
                     "by this rig's per-call dispatch constant + int32 "
                     "counts pull — see ROUND4_NOTES.md)",
           "value": round(v, 3), "unit": "Mpix/s",
           "smooth_f64_mpix_s": round(v, 3)}
    try:
        from distributedmandelbrot_tpu.ops import (DeepTileSpec,
                                                   compute_counts_perturb)

        def leg(px):
            dspec = DeepTileSpec("-0.77568376995", "0.13646737005",
                                 1e-10, width=px, height=px)

            def run_perturb():
                compute_counts_perturb(dspec, 50000, dtype=np.float32)
                return np.zeros(())

            return _mpix(px * px, _time_chain(run_perturb,
                                              max(1, repeats - 1)))

        v_p = leg(side)
        out["perturb_f32_mpix_s"] = round(v_p, 3)
        # Production-amortized probe: same view/budget at 1024^2, where
        # the per-call constant shrinks 4x relative to the pixels (the
        # BASELINE config fixes view and budget, not tile size — and
        # production tiles are 4096^2).
        v_p2 = leg(1024)
        out["perturb_f32_1024_mpix_s"] = round(v_p2, 3)
        out["value"] = round(max(v, v_p, v_p2), 3)
    except Exception as e:  # never let one path kill the bench sweep
        print(f"# config4 perturbation skipped: {type(e).__name__}: {e}",
              file=sys.stderr)
    return out


def bench_deepslow(repeats: int) -> dict:
    """Slow-dynamics deep zoom: the period-6 bond point of the main
    cardioid (c = 3/8 + i sqrt(3)/8, center exact to 40 digits) at span
    1e-15 and budget 100000 — a parabolic window where every pixel runs
    the full orbit.  The classic pathological deep-zoom case; reports
    the exact perturbation scan and the (auto-selected by default) BLA
    fast path
    (ops/bla.py — approximate by documented contract; on TPU the two
    are bit-identical on this all-interior view, pinned by tests, and
    the artifact carries the measured ``bla_agreement`` rather than
    asserting it, so a CPU-fallback sweep completes either way)."""
    from distributedmandelbrot_tpu.ops import (DeepTileSpec,
                                               compute_counts_perturb)
    from distributedmandelbrot_tpu.ops.bla import (BOND_POINT_IM,
                                                   BOND_POINT_RE)

    side, mi = 256, 100_000
    spec = DeepTileSpec(BOND_POINT_RE, BOND_POINT_IM, 1e-15,
                        width=side, height=side)

    outs = {}

    def leg(bla):
        def run():
            outs[bla] = compute_counts_perturb(spec, mi, bla=bla)[0]
            return np.zeros(())
        return run

    t_exact = _time_chain(leg(False), max(1, repeats - 1))
    t_bla = _time_chain(leg(True), max(1, repeats - 1))
    # The headline leg runs the ACTUAL default (bla=None -> auto-probe,
    # cached after the first call), so the artifact measures what a
    # default render achieves rather than assuming the probe's choice
    # (round-4 review finding).
    t_auto = _time_chain(leg(None), max(1, repeats - 1))
    # Reported, not asserted: on TPU the two are bit-identical here
    # (pinned by tests); a CPU-fallback run could flip a marginal
    # boundary lane via FMA-contraction trajectory drift, which should
    # show in the artifact rather than abort the sweep.
    agree = float((outs[False] == outs[True]).mean())
    # Round 4: the auto-probe (bla=None, the default every caller gets)
    # selects BLA on this view, so the headline value is the BLA rate —
    # what a default render actually achieves — with the exact-scan
    # reference rate and the measured agreement alongside.
    agree_auto = float((outs[False] == outs[None]).mean())
    return {"metric": f"deep-slow parabolic bond point {side}^2 mi={mi} "
                      "span 1e-15 (value = the DEFAULT bla=None "
                      "auto-probed path, measured; exact scan and "
                      "forced BLA kept as reference legs)",
            "value": round(_mpix(side * side, t_auto), 3),
            "unit": "Mpix/s",
            "exact_mpix_s": round(_mpix(side * side, t_exact), 3),
            "bla_mpix_s": round(_mpix(side * side, t_bla), 3),
            "bla_speedup": round(t_exact / t_bla, 1),
            "bla_agreement": round(agree, 6),
            "auto_agreement_vs_exact": round(agree_auto, 6)}


def bench_config5(repeats: int, segment: int) -> dict:
    """BASELINE config 5 (local-mesh stand-in for v5e-16): 60-frame zoom,
    every frame's tile batch chained on device in one dispatch.
    True multi-host needs a slice; this measures the per-host pipeline."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    _, mesh, _ = _mesh_and_kernel()
    n = max(8, mesh.devices.size)
    frames = 60
    tile = 256  # keep the stand-in affordable; rate scales to 4096
    base_span = 3.0

    all_params = np.empty((frames * n, 3))
    for f in range(frames):
        span = base_span * (0.93 ** f)
        for i in range(n):
            all_params[f * n + i] = (
                SEAHORSE[0] - span / 2 + (i % 4) * span / 4,
                SEAHORSE[1] - span / 2 + (i // 4) * span / 4,
                span / 4 / (tile - 1))

    from distributedmandelbrot_tpu.ops.pallas_escape import pallas_available
    if pallas_available():
        fn = _pallas_chain(all_params, tile, 1000)
        label = "pallas"
    else:
        fn = _xla_chain(mesh, all_params,
                        np.full(frames * n, 1000, np.int64), tile, segment,
                        np.float32)
        label = "xla"

    v = _mpix(frames * n * tile * tile, _time_chain(fn, max(1, repeats - 1)))
    out = {"metric": f"config5 zoom-animation {frames}f x {n}x{tile}^2 "
                     f"mi=1000 ({mesh.devices.size} device(s), {label})",
           "value": round(v, 2), "unit": "Mpix/s"}
    if pallas_available():
        try:
            # Round-5 verdict item 7: one production-shaped point, so
            # "rate scales to 4096" is measured, not asserted — a short
            # 4-frame leg at the production tile size (4 frames x 4
            # tiles of 4096^2 chained in one dispatch), with the same
            # latency decomposition as the tile-shape config.
            big, bf, bn = 4096, 4, 4
            big_params = np.empty((bf * bn, 3))
            for f in range(bf):
                span = base_span * (0.93 ** f)
                for i in range(bn):
                    big_params[f * bn + i] = (
                        SEAHORSE[0] - span / 2 + (i % 2) * span / 2,
                        SEAHORSE[1] - span / 2 + (i // 2) * span / 2,
                        span / 2 / (big - 1))
            df = _device_fields(
                lambda r: _pallas_chain(big_params, big, 1000, reps=r),
                bf * bn * big * big, repeats)
            out["tile4096_4f_mpix_s"] = df["benched_mpix_s"]
            _copy_device_fields(out, df, prefix="tile4096_4f_")
        except Exception as e:
            print(f"# config5 4096-class leg skipped: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
    return out


# Boundary-only views: windows crossing NO provable interior (verified
# 0.0000% mandelbrot_interior coverage at these coordinates), where the
# interior shortcut cannot help and throughput reverts to the raw masked
# loop — the number that governs worst-case renders.  The ship window has
# no closed-form interior at all (family_interior returns None).
WORST_VIEWS = {
    "filament": {"center": (-0.7436447, 0.1318252), "span": 2e-3,
                 "max_iter": 2000, "burning": False},
    "ship": {"center": (-1.7443, -0.0356), "span": 0.01,
             "max_iter": 1000, "burning": True},
}


# All-interior control window for the roofline fields: every pixel of
# this view sits inside the main cardioid, so with the interior shortcut
# and cycle probe disabled the kernel provably executes EXACTLY
# pixels * (max_iter - 1) iterations — the one payload whose Giter/s
# needs no cost model.
UNIFORM_VIEW = ((-0.1, 0.0), 0.2)


def bench_worstcase(repeats: int, *, tile: int | None = None,
                    tiles: int | None = None) -> dict:
    """Boundary-only views, raw (shortcut-less) vs full-shortcut numbers
    per view.  The headline `value` is the WORST per-view best at the
    PRODUCTION call class (64 Mpix per dispatch — the same per-call
    pixel count as the headline config and a 4-tile 4096^2 farm batch):
    the throughput floor a farm actually sees on views the interior
    shortcut cannot touch.  The legacy 16-tile-class floor is kept as
    ``floor_16x1024_mpix_s`` for round-over-round comparability — that
    config is dominated by the rig's ~70-75 ms per-call dispatch+sync
    constant (a 16.7 Mpix dispatch cannot bench above ~230 Mpix/s here
    regardless of kernel speed; ROUND4_NOTES.md), which the
    ``*_device_mpix_s`` / ``call_overhead_s`` fields make explicit.
    Roofline fields (``giter_s``, ``vpu_util_frac``) come from the
    all-interior uniform control, whose executed iteration count is
    exact.  Runs the Pallas kernel on TPU (compiled) and falls back to
    the XLA chain off-TPU (the interpreter would distort raw-loop
    timing; production-class and roofline fields are TPU-only)."""
    from distributedmandelbrot_tpu.ops.pallas_escape import pallas_available

    jax, mesh, _ = _mesh_and_kernel()
    on_tpu = pallas_available()
    if tile is None:
        tile = 1024 if on_tpu else 256
    if tiles is None:
        tiles = 16 if on_tpu else 4
    prod_tiles = 64  # the headline's per-call pixel class at tile=1024
    out: dict = {}
    skipped: list[str] = []
    floor16 = float("inf")
    floor_prod = float("inf")
    for name, view in WORST_VIEWS.items():
        params = _grid_params(view["center"], view["span"], tile, tiles)
        mi = view["max_iter"]
        pixels = tiles * tile * tile
        per_path: dict[str, float] = {}
        if on_tpu:
            kw = {"burning": True} if view["burning"] else {}
            per_path["raw"] = pixels / _time_chain(
                _pallas_chain(params, tile, mi, interior_check=False,
                              cycle_check=False, scout_segments=0, **kw),
                repeats) / 1e6
            per_path["full"] = pixels / _time_chain(
                _pallas_chain(params, tile, mi, **kw), repeats) / 1e6
            # Production call class: benched + latency-decomposed.
            params_p = _grid_params(view["center"], view["span"], tile,
                                    prod_tiles)
            pixels_p = prod_tiles * tile * tile
            df = _device_fields(
                lambda r, p=params_p, m=mi, kw=kw: _pallas_chain(
                    p, tile, m, reps=r, **kw), pixels_p, repeats)
            out[f"{name}_prod_mpix_s"] = df["benched_mpix_s"]
            if "device_mpix_s" in df:
                out[f"{name}_prod_device_mpix_s"] = df["device_mpix_s"]
                out[f"{name}_call_overhead_s"] = df["call_overhead_s"]
            # (prefixed layout predates _copy_device_fields; field names
            # still come from the same _device_fields source)
            floor_prod = min(floor_prod, df["benched_mpix_s"])
        elif not view["burning"]:
            # CPU fallback control: XLA chain only (no ship support in
            # the sharded XLA path), marked by the cpu_fallback flag.
            mrds = np.full(tiles, mi, np.int64)
            per_path["raw"] = pixels / _time_chain(
                _xla_chain(mesh, params, mrds, tile, 256, np.float32,
                           interior_check=False, cycle_check=False),
                repeats) / 1e6
            per_path["full"] = pixels / _time_chain(
                _xla_chain(mesh, params, mrds, tile, 256, np.float32),
                repeats) / 1e6
        else:
            skipped.append(name)
            continue
        for path, v in per_path.items():
            out[f"{name}_{path}_mpix_s"] = round(v, 2)
        floor16 = min(floor16, max(per_path.values()))
    if on_tpu:
        # Roofline: exact-work uniform control (see UNIFORM_VIEW note).
        mi_u = 2000
        params_u = _grid_params(*UNIFORM_VIEW, tile, tiles)
        pixels_u = tiles * tile * tile
        out.update({k: v for k, v in _device_fields(
            lambda r: _pallas_chain(params_u, tile, mi_u, reps=r,
                                    interior_check=False,
                                    cycle_check=False,
                                    scout_segments=0),
            pixels_u, repeats,
            iters_exact=pixels_u * (mi_u - 1)).items()
            if k in ("giter_s", "vpu_util_frac")})
    if skipped:
        # No silent coverage caps: a CPU run measures fewer views than a
        # TPU run, and the floor must say so.
        out["skipped_views"] = skipped
    floor = floor_prod if on_tpu else floor16
    out = {
        "metric": "worst-case boundary views (no provable interior; "
                  + ("floor of per-view best at the production "
                     f"{prod_tiles}x{tile}^2 call class; legacy "
                     f"{tiles}x{tile}^2 floor kept" if on_tpu else
                     f"CPU fallback floor at {tiles}x{tile}^2")
                  + (f"; skipped: {','.join(skipped)}" if skipped else "")
                  + ")",
        "value": round(floor, 2), "unit": "Mpix/s",
        "vs_baseline": round(floor / NORTH_STAR_MPIX_S, 4),
        f"floor_{tiles}x{tile}_mpix_s": round(floor16, 2),
        **out,
    }
    return out


def bench_tileshape(repeats: int) -> dict:
    """The production tile shape (4096^2 — THE chunk size of the
    reference, ``DataChunk.cs:20,27``) at the headline view/budget,
    latency-decomposed (round-3 verdict item 2).  The round-3 artifact
    gap (178.8 Mpix/s single-4096^2 vs 586 headline) is the per-call
    dispatch+sync constant, not the tile shape: at equal per-call pixel
    counts the 4096^2 shape matches or beats the 1024^2 batch (fewer,
    longer grid programs) — this config pins that equivalence in the
    driver artifacts.  TPU-only (the XLA fallback would measure the
    interpreter, and production 4096^2 tiles are a TPU workload)."""
    from distributedmandelbrot_tpu.ops.pallas_escape import pallas_available

    if not pallas_available():
        return {"metric": "4096^2 production tile shape (TPU only)",
                "value": 0.0, "unit": "Mpix/s", "skipped": True}
    mi = 1000
    out: dict = {}
    # WORKLOAD-MATCHED tilings of the same windows (round-4 review
    # finding: _grid_params' sub-window scheme gives different-content
    # views per batch size, which would compare workloads, not tile
    # shapes).  Each 4096^2 tile over a quadrant of the headline window
    # is re-tiled as 16x1024^2 at the SAME pixel pitch and offsets, so
    # both shapes compute the same pixel set and the rate difference is
    # the tile shape alone.
    span = 0.02
    x0, y0 = SEAHORSE[0] + 0.01 - span / 2, SEAHORSE[1] + 0.01 - span / 2
    quad = span / 2
    pitch = quad / 4095  # a 4096^2 tile spans one quadrant

    def quads(n_quads):
        return [(x0 + (q % 2) * quad, y0 + (q // 2) * quad)
                for q in range(n_quads)]

    def params_4096(n_quads):
        return np.asarray([[qx, qy, pitch] for qx, qy in quads(n_quads)])

    def params_1024(n_quads):
        return np.asarray([[qx + 1024 * bi * pitch,
                            qy + 1024 * bj * pitch, pitch]
                           for qx, qy in quads(n_quads)
                           for bj in range(4) for bi in range(4)])

    configs = [("tile4096x1", 4096, params_4096(1)),
               ("tile4096x4", 4096, params_4096(4)),
               ("tile1024x16", 1024, params_1024(1)),
               ("tile1024x64", 1024, params_1024(4))]
    for name, tile, params in configs:
        pixels = params.shape[0] * tile * tile
        df = _device_fields(
            lambda r, p=params, t=tile: _pallas_chain(p, t, mi, reps=r),
            pixels, repeats)
        out[f"{name}_mpix_s"] = df["benched_mpix_s"]
        _copy_device_fields(out, df, prefix=f"{name}_")
    return {
        "metric": "production tile shape: 4096^2 vs pitch-matched "
                  f"1024^2 re-tilings of the same windows, mi={mi} "
                  "(benched = tunnel-inclusive; device = chained delta)",
        "value": out["tile4096x4_mpix_s"], "unit": "Mpix/s",
        "vs_baseline": round(out["tile4096x4_mpix_s"] / NORTH_STAR_MPIX_S,
                             4),
        **out,
    }


def _hist_fields(registry, fields: dict) -> dict:
    """p50/p99 rows from a metrics Registry's histogram families — the
    ONE copy of the field-naming rule, shared by the farm and serve
    configs so BENCH artifacts stay comparable round over round.
    Families with no observations are omitted, not zero-filled."""
    out = {}
    for key, family in fields.items():
        p50 = registry.family_percentile(family, 50)
        if p50 is None:
            continue
        out[f"{key}_p50_s"] = round(p50, 6)
        out[f"{key}_p99_s"] = round(registry.family_percentile(family, 99),
                                    6)
    return out


def _phase_sums(registry, family: str, label: str) -> dict:
    """Per-label-value time sums (seconds) of a histogram family — how
    the bench reads the backend's dispatch/materialize split out of the
    registry now that the racy ``phase_us`` dict is gone."""
    out: dict = {}
    for name, kind, _help, children in registry.collect():
        if name != family or kind != "histogram":
            continue
        for child in children:
            _, total, _count = child.state()
            lv = dict(child.labels).get(label, "")
            out[lv] = out.get(lv, 0.0) + total
    return out


def bench_farm(repeats: int, *, levels: str = "3:1000",
               definition: int = 4096, batch_size: int = 3,
               backend_name: str = "auto", window: int = 8,
               depth: int = 2, upload_lanes: int = 0,
               grant_batch: int = 0) -> dict:
    """Production shape: coordinator + worker over loopback TCP, 4096^2
    chunks, batched dispatch, full pipeline (lease -> compute -> upload ->
    persist).  Real materialization everywhere — on this rig the device->
    host tunnel (~35 MB/s) dominates; on a co-located TPU host the same
    path runs at PCIe rates.

    The worker runs the pipelined executor by default (``window`` tiles
    in flight across lease/dispatch/materialize/upload, ``depth`` kernels
    per device); ``window=0`` (CLI: ``--farm-window 0``) is the legacy
    two-stage-overlap control, so the delta between the two IS the
    pipelining win.  The JSON line carries a per-phase breakdown (lease /
    compute / upload / persist seconds and shares, plus the device idle
    fraction) and, when pipelined, the per-stage occupancy/bubble split
    that localizes any remaining gap to one stage; run with
    ``backend_name="native"`` (CLI: ``--farm-backend native``) as the
    no-device control — any phase share that persists there is framework
    overhead, not tunnel."""
    import tempfile

    from distributedmandelbrot_tpu.cli import parse_level_settings
    from distributedmandelbrot_tpu.coordinator import EmbeddedCoordinator
    from distributedmandelbrot_tpu.worker import (DistributerClient, Worker,
                                                  auto_backend)

    settings = parse_level_settings(levels)
    n_tiles = sum(s.level * s.level for s in settings)
    per_round: list[tuple[float, int]] = []

    with tempfile.TemporaryDirectory() as tmp, \
            EmbeddedCoordinator(tmp, settings) as co:
        if backend_name == "auto":
            backend = auto_backend(definition=definition)
        else:
            from distributedmandelbrot_tpu.cli import _make_backend
            backend = _make_backend(backend_name, "f32", "auto",
                                    definition=definition)
        client = DistributerClient("127.0.0.1", co.distributer_port)
        worker = Worker(client, backend, batch_size=batch_size,
                        overlap_io=True, window=window, depth=depth,
                        upload_lanes=upload_lanes, grant_batch=grant_batch)
        # warmup: compile the kernel outside the timed window
        from distributedmandelbrot_tpu.core.workload import Workload
        backend.compute_batch([Workload(settings[0].level,
                                        settings[0].max_iter, 0, 0)])
        from distributedmandelbrot_tpu.obs import names as obs_names
        wreg = worker.counters.registry
        phase0 = _phase_sums(wreg, obs_names.HIST_BACKEND_PHASE_SECONDS,
                             "phase")
        t0 = time.perf_counter()
        if window > 0:
            worker.run_until_drained()
        else:
            while True:
                r0 = time.perf_counter()
                done_before = worker.counters.get("tiles_computed")
                got = worker.run_once()
                if not got:
                    break
                n_round = worker.counters.get("tiles_computed") - done_before
                per_round.append((time.perf_counter() - r0, n_round))
        co.wait_saves_settled(expected_accepted=n_tiles, timeout=600)
        total = time.perf_counter() - t0
        wc = worker.counters.snapshot()
        cc = co.counters.snapshot()
        hist = _hist_fields(co.registry, {
            "grant": obs_names.HIST_GRANT_SECONDS,
            "persist": obs_names.HIST_PERSIST_SECONDS})
        hist.update(_hist_fields(wreg, {
            "compute": obs_names.HIST_WORKER_COMPUTE_SECONDS,
            "upload": obs_names.HIST_WORKER_UPLOAD_SECONDS}))
        phase1 = _phase_sums(wreg, obs_names.HIST_BACKEND_PHASE_SECONDS,
                             "phase")
        stage_stats = (worker.pipeline.stage_stats()
                       if worker.pipeline is not None else None)
        backend_cls = type(backend).__name__
        # Cross-process critical-path attribution: the coordinator's
        # trace joined with the worker spans it ingested over the wire
        # (obs/spans.py) — the "where exactly" view beside the phase
        # sums below.
        from distributedmandelbrot_tpu.obs.spans import critical_path
        farm_trace = critical_path(co.trace.spans(), co.spans)

    if window > 0:
        # Per-tile turnaround = dispatch->materialized, straight from the
        # executor's per-tile histogram.
        p50 = wreg.family_percentile(
            obs_names.HIST_WORKER_COMPUTE_SECONDS, 50) or float("nan")
        mode = f"pipelined w{window}d{depth}"
    else:
        # One per-tile sample per tile actually leased that round (the
        # last round is usually short).
        per_tile = sorted(dt / k for dt, k in per_round if k
                          for _ in range(k))
        p50 = per_tile[len(per_tile) // 2] if per_tile else float("nan")
        mode = "classic overlap"
    pixels = n_tiles * definition * definition
    out = {"metric": f"farm e2e {levels} {n_tiles}x{definition}^2 "
                     f"batched-dispatch ({backend_cls}, {mode}, incl. "
                     f"upload + persist)",
           "value": round(_mpix(pixels, total), 2), "unit": "Mpix/s",
           "p50_tile_turnaround_s": round(p50, 3),
           "total_s": round(total, 2)}
    # Phase breakdown.  lease/compute are on the worker's critical path
    # in classic mode; upload rides the overlap-IO thread and persist the
    # coordinator's save tasks, so their shares can exceed what the wall
    # clock shows — a share > ~1.0 of any of them means the pipeline is
    # hiding it well, not that the clock is wrong.  (Pipelined, ALL four
    # run off the critical path of each other; the stage occupancies
    # below are the honest account.)  Device idle fraction ~= the
    # critical path's non-compute share (device backends only).
    phases = {"lease": wc.get("lease_us", 0) / 1e6,
              "compute": wc.get("compute_us", 0) / 1e6,
              "upload": wc.get("upload_us", 0) / 1e6,
              "persist": cc.get("persist_us", 0) / 1e6}
    for name, secs in phases.items():
        out[f"{name}_s"] = round(secs, 2)
        out[f"{name}_share"] = round(secs / total, 3) if total else 0.0
    if phase1:
        # PallasBackend's split of compute: host dispatch vs materialize
        # (device completion wait + D2H — the tunnel, on this rig).
        # Warmup ran before t0, so the pre-run sums are subtracted.
        out["compute_dispatch_s"] = round(
            phase1.get("dispatch", 0.0) - phase0.get("dispatch", 0.0), 2)
        out["compute_materialize_s"] = round(
            phase1.get("materialize", 0.0)
            - phase0.get("materialize", 0.0), 2)
    out["device_idle_frac"] = round(
        max(0.0, 1.0 - phases["compute"] / total), 3) if total else 0.0
    if stage_stats is not None:
        # The tentpole's acceptance metric: where the remaining bubbles
        # are.  A stage at occupancy ~1.0 is the bottleneck; every other
        # stage's bubble is time it spent waiting on it.
        out["pipe_wall_s"] = stage_stats["wall_s"]
        for name, st in stage_stats["stages"].items():
            out[f"pipe_{name}_busy_s"] = st["busy_s"]
            out[f"pipe_{name}_occupancy"] = st["occupancy"]
            out[f"pipe_{name}_bubble"] = st["bubble"]
        for i, lane in enumerate(stage_stats.get("lanes", [])):
            out[f"pipe_lane{i}_occupancy"] = lane["occupancy"]
    # Wire accounting for the session tier: bytes that actually crossed
    # the socket per codec, and blocking round trips per tile (the
    # 1-RTT-steady-state target of the lease piggyback).
    out["farm_wire_raw_bytes"] = wc.get(obs_names.WIRE_RAW_BYTES, 0)
    out["farm_wire_compressed_bytes"] = \
        wc.get(obs_names.WIRE_COMPRESSED_BYTES, 0)
    rtts = wc.get(obs_names.WORKER_WIRE_RTTS, 0)
    out["farm_rtts_per_tile"] = round(rtts / n_tiles, 2)
    # Batched-grant efficiency: tiles granted per DEDICATED lease round
    # trip — the lease stage's exchanges, empty drain probes included;
    # grants piggybacked on upload acks ride an RTT the upload already
    # owed, so they amortize to zero here.  >= 4 vs the exactly-1 of
    # the one-grant era is the REQN tentpole's acceptance bar.
    lease_rtts = wc.get(obs_names.PIPELINE_LEASE_EXCHANGES, 0)
    granted = cc.get("workloads_granted", 0)
    out["farm_grants_per_rtt"] = \
        round(granted / lease_rtts, 2) if lease_rtts else 0.0
    out["farm_grant_batches"] = cc.get(obs_names.COORD_GRANT_BATCHES, 0)
    # Group-commit shape: index flushes and average tiles per flush —
    # the persist-amortization half of the tentpole.
    commits = cc.get(obs_names.STORE_GROUP_COMMITS, 0)
    flushed = cc.get(obs_names.STORE_FLUSH_TILES, 0)
    out["persist_group_commits"] = commits
    out["persist_flush_tiles_avg"] = \
        round(flushed / commits, 2) if commits else 0.0
    out["farm_sessions"] = wc.get(obs_names.WORKER_SESSIONS_OPENED, 0)
    if farm_trace.get("tiles"):
        out["farm_trace_tiles"] = farm_trace["tiles"]
        out["farm_trace_attributed"] = farm_trace["attributed_tiles"]
        for phase in ("queue", "compute", "d2h", "upload", "persist",
                      "other"):
            out[f"farm_trace_{phase}_s"] = farm_trace[f"{phase}_s"]
            out[f"farm_trace_{phase}_share"] = \
                farm_trace[f"{phase}_share"]
    out.update(hist)
    return out


def _farm_multi_point(workers: int, *, levels: str, definition: int,
                      batch_size: int, backend_name: str, window: int,
                      depth: int, upload_lanes: int,
                      grant_batch: int = 0) -> dict:
    """One scaling-curve point: ``workers`` subprocesses against a fresh
    coordinator; returns the full per-point stats dict."""
    import os
    import subprocess
    import tempfile

    from distributedmandelbrot_tpu.cli import parse_level_settings
    from distributedmandelbrot_tpu.coordinator import EmbeddedCoordinator
    from distributedmandelbrot_tpu.obs import names as obs_names
    from distributedmandelbrot_tpu.obs.spans import critical_path

    settings = parse_level_settings(levels)
    n_tiles = sum(s.level * s.level for s in settings)
    with tempfile.TemporaryDirectory() as tmp, \
            EmbeddedCoordinator(tmp, settings) as co:
        stats_paths = [os.path.join(tmp, f"worker{i}-stats.json")
                       for i in range(workers)]
        log_paths = [os.path.join(tmp, f"worker{i}.log")
                     for i in range(workers)]
        cmd = [sys.executable, "-m", "distributedmandelbrot_tpu", "worker",
               "--host", "127.0.0.1", "--port", str(co.distributer_port),
               "--backend", backend_name, "--batch-size", str(batch_size),
               "--window", str(window), "--depth", str(depth),
               "--upload-lanes", str(upload_lanes)]
        if grant_batch:
            cmd += ["--grant-batch", str(grant_batch)]
        t0 = time.perf_counter()
        procs = []
        for stats_path, log_path in zip(stats_paths, log_paths):
            log = open(log_path, "w")
            procs.append((subprocess.Popen(
                cmd + ["--stats-json", stats_path],
                stdout=log, stderr=subprocess.STDOUT), log))
        try:
            for proc, log in procs:
                rc = proc.wait(timeout=1800)
                log.close()
                if rc != 0:
                    tail = open(log.name).read()[-2000:]
                    raise RuntimeError(
                        f"worker subprocess exited {rc}:\n{tail}")
        finally:
            for proc, log in procs:
                if proc.poll() is None:
                    proc.kill()
                if not log.closed:
                    log.close()
        co.wait_saves_settled(expected_accepted=n_tiles, timeout=600)
        total = time.perf_counter() - t0
        cc = co.counters.snapshot()
        farm_trace = critical_path(co.trace.spans(), co.spans)
        per_worker = []
        for stats_path in stats_paths:
            with open(stats_path, encoding="utf-8") as fh:
                per_worker.append(json.load(fh))

    def wsum(key: str) -> int:
        return sum(w["counters"].get(key, 0) for w in per_worker)

    pixels = n_tiles * definition * definition
    out = {"metric": f"farm e2e {levels} {n_tiles}x{definition}^2 "
                     f"{workers} workers (subprocess, pipelined "
                     f"w{window}d{depth}, incl. upload + persist)",
           "value": round(_mpix(pixels, total), 2), "unit": "Mpix/s",
           "total_s": round(total, 2),
           "farm_workers": workers,
           "tiles_per_worker": [
               w["counters"].get("tiles_computed", 0) for w in per_worker],
           "farm_wire_raw_bytes": wsum(obs_names.WIRE_RAW_BYTES),
           "farm_wire_compressed_bytes":
               wsum(obs_names.WIRE_COMPRESSED_BYTES),
           "farm_rtts_per_tile": round(
               wsum(obs_names.WORKER_WIRE_RTTS) / n_tiles, 2),
           "farm_sessions": wsum(obs_names.WORKER_SESSIONS_OPENED),
           "farm_session_fallbacks":
               wsum(obs_names.WORKER_SESSION_FALLBACKS),
           "coord_connections":
               cc.get(obs_names.COORD_CONNECTIONS_ACCEPTED, 0),
           "persist_s": round(cc.get("persist_us", 0) / 1e6, 2)}
    # Same definition as the single-worker leg: tiles granted per
    # dedicated lease exchange across the fleet (piggybacked grants ride
    # upload acks at zero marginal RTT).
    lease_rtts = wsum(obs_names.PIPELINE_LEASE_EXCHANGES)
    granted = cc.get("workloads_granted", 0)
    out["farm_grants_per_rtt"] = \
        round(granted / lease_rtts, 2) if lease_rtts else 0.0
    out["farm_grant_batches"] = cc.get(obs_names.COORD_GRANT_BATCHES, 0)
    commits = cc.get(obs_names.STORE_GROUP_COMMITS, 0)
    flushed = cc.get(obs_names.STORE_FLUSH_TILES, 0)
    out["persist_group_commits"] = commits
    out["persist_flush_tiles_avg"] = \
        round(flushed / commits, 2) if commits else 0.0
    for i, w in enumerate(per_worker):
        for j, lane in enumerate(
                w.get("stage_stats", {}).get("lanes", [])):
            out[f"pipe_w{i}_lane{j}_occupancy"] = lane["occupancy"]
    if farm_trace.get("tiles"):
        out["farm_trace_tiles"] = farm_trace["tiles"]
        out["farm_trace_attributed"] = farm_trace["attributed_tiles"]
        for phase in ("queue", "compute", "d2h", "upload", "persist",
                      "other"):
            out[f"farm_trace_{phase}_s"] = farm_trace[f"{phase}_s"]
            out[f"farm_trace_{phase}_share"] = \
                farm_trace[f"{phase}_share"]
    return out


def bench_farm_multi(repeats: int, *, workers: int = 4,
                     levels: str = "3:1000", definition: int = 4096,
                     batch_size: int = 3, backend_name: str = "auto",
                     window: int = 8, depth: int = 2,
                     upload_lanes: int = 0, grant_batch: int = 0) -> dict:
    """The real farm shape: N worker *subprocesses* racing one
    coordinator over loopback TCP, each with its own device context,
    pipelined executor, and session lanes.  Aggregate Mpix/s is wall
    clock from first spawn to the last chunk fsynced; per-worker wire
    and lane metrics come back through ``dmtpu worker --stats-json``
    (subprocess counters are invisible to this process otherwise), and
    critical-path attribution joins the coordinator's trace with every
    worker's pushed spans exactly as the single-worker config does.

    Runs a 1 -> ``workers`` scaling curve (doubling worker counts, each
    point a fresh coordinator + store) and reports the top point as the
    headline, with the per-point aggregate Mpix/s / grants-per-RTT /
    persist-flush shape in ``scaling_curve`` — the cross-process answer
    to "does the farm leg actually scale out, and what saturates first".
    Per-worker lanes stay auto-tuned (one per local device) and every
    worker is identically configured, so a sub-linear step in the curve
    localizes to the shared coordinator/store, not worker skew."""
    counts = []
    n = 1
    while n < workers:
        counts.append(n)
        n *= 2
    counts.append(workers)
    kwargs = dict(levels=levels, definition=definition,
                  batch_size=batch_size, backend_name=backend_name,
                  window=window, depth=depth, upload_lanes=upload_lanes,
                  grant_batch=grant_batch)
    curve = []
    for c in counts:
        point = _farm_multi_point(c, **kwargs)
        curve.append(point)
    out = dict(curve[-1])
    base = curve[0]["value"]
    out["scaling_curve"] = [
        {"workers": point["farm_workers"],
         "mpix_s": point["value"],
         "total_s": point["total_s"],
         "speedup_vs_1": round(point["value"] / base, 2) if base else 0.0,
         "grants_per_rtt": point["farm_grants_per_rtt"],
         "rtts_per_tile": point["farm_rtts_per_tile"],
         "persist_group_commits": point["persist_group_commits"],
         "persist_flush_tiles_avg": point["persist_flush_tiles_avg"]}
        for point in curve]
    return out


def bench_serve(repeats: int, *, levels: str = "2:256",
                backend_name: str = "auto", storm_clients: int = 16,
                warm_fetches: int = 32) -> dict:
    """Serving-gateway shape: coordinator + gateway + one worker, measured
    from the client side of the wire.  Three scenarios:

    - cold miss: one fetch of an uncomputed tile — the full compute-on-read
      path (prioritize -> farm compute -> persist -> promote -> serve);
    - warm hit: repeated fetches of a cached tile — the tier-1 ceiling
      (decoded-tile LRU, no store traffic);
    - coalesced storm: N concurrent clients for one tile that is on disk
      but not in tier 1 — single-flight fan-out of one store read.

    Tile payloads ride the real TCP loopback, so warm numbers include the
    codec + socket cost a production viewer would pay."""
    import tempfile
    import threading

    from distributedmandelbrot_tpu.cli import parse_level_settings
    from distributedmandelbrot_tpu.coordinator import EmbeddedCoordinator
    from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
    from distributedmandelbrot_tpu.viewer import DataClient, FetchStatus
    from distributedmandelbrot_tpu.worker import (DistributerClient, Worker,
                                                  auto_backend)

    settings = parse_level_settings(levels)
    n_tiles = sum(s.level * s.level for s in settings)
    level = settings[0].level
    hot = (level, level - 1, level - 1)  # last in the frontier walk
    storm_tile = (level, 0, min(1, level - 1))

    with tempfile.TemporaryDirectory() as tmp, \
            EmbeddedCoordinator(tmp, settings) as co:
        if backend_name == "auto":
            backend = auto_backend()
        else:
            from distributedmandelbrot_tpu.cli import _make_backend
            backend = _make_backend(backend_name, "f32", "auto")
        stop = threading.Event()
        worker = Worker(DistributerClient("127.0.0.1", co.distributer_port),
                        backend, overlap_io=False)
        wt = threading.Thread(target=worker.run_forever,
                              kwargs=dict(poll_interval=0.02, stop=stop),
                              daemon=True)
        wt.start()
        try:
            client = DataClient("127.0.0.1", co.gateway_port, timeout=600)
            # Cold miss: the hot tile is last in the frontier, so this
            # latency is compute-on-read's queue jump, not frontier luck.
            t0 = time.perf_counter()
            _, status = client.fetch(*hot)
            cold_s = time.perf_counter() - t0
            assert status is FetchStatus.OK, status
            # Warm hits: tier-1 fan-out of the tile just promoted.
            warm_rates = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                for _ in range(warm_fetches):
                    _, status = client.fetch(*hot)
                    assert status is FetchStatus.OK, status
                dt = time.perf_counter() - t0
                warm_rates.append(_mpix(warm_fetches * CHUNK_PIXELS, dt))
            warm_rates.sort()
            warm_mpix = warm_rates[len(warm_rates) // 2]
            # Storm: wait for the farm to finish so the storm tile is on
            # disk (tier 2) but has never been fetched (not in tier 1).
            co.wait_saves_settled(expected_accepted=n_tiles, timeout=600)
            barrier = threading.Barrier(storm_clients + 1)
            errors: list = []

            def storm():
                try:
                    c = DataClient("127.0.0.1", co.gateway_port, timeout=600)
                    barrier.wait()
                    _, s = c.fetch(*storm_tile)
                    assert s is FetchStatus.OK, s
                    c.close()
                except BaseException as e:
                    errors.append(e)

            threads = [threading.Thread(target=storm, daemon=True)
                       for _ in range(storm_clients)]
            for t in threads:
                t.start()
            barrier.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join(timeout=600)
            storm_s = time.perf_counter() - t0
            assert not errors, errors[:2]
            cc = co.counters.snapshot()
            # Client-observed latency from the gateway's own histogram
            # (all outcomes merged), plus the tier hit-ratio gauges —
            # the acceptance signal that the telemetry pipeline saw the
            # same traffic the bench generated.
            from distributedmandelbrot_tpu.obs import names as obs_names
            hist = _hist_fields(co.registry, {
                "gateway": obs_names.HIST_GATEWAY_REQUEST_SECONDS})
            gauges = co.registry.snapshot()["gauges"]
            tier1 = gauges.get(obs_names.GAUGE_TIER1_HIT_RATIO, 0.0)
        finally:
            stop.set()
            wt.join(timeout=60)

    return {"metric": f"serve gateway {levels} warm-hit tier-1 fan-out "
                      f"({type(backend).__name__} farm behind)",
            "value": round(warm_mpix, 2), "unit": "Mpix/s",
            "cold_miss_s": round(cold_s, 3),
            "warm_hit_qps": round(warm_mpix * 1e6 / CHUNK_PIXELS, 1),
            "storm_clients": storm_clients,
            "storm_wall_s": round(storm_s, 3),
            "storm_mpix_s": round(
                _mpix(storm_clients * CHUNK_PIXELS, storm_s), 2),
            "coalesce_leaders": cc.get("coalesce_leaders", 0),
            "coalesce_followers": cc.get("coalesce_followers", 0),
            "tile_cache_hits": cc.get("tile_cache_hits", 0),
            "tier1_hit_ratio": round(tier1, 3),
            **hist}


def bench_recovery(repeats: int, *, levels: str = "64:100",
                   checkpoint_fraction: float = 0.8,
                   hold_back: int = 64) -> dict:
    """Crash-recovery shape (no accelerator): how fast a coordinator gets
    back to granting after a restart, and what the durability checkpoint
    buys over a full index replay.

    Builds an index of NEVER entries (16 bytes each — pure index
    traffic, no chunk blobs), writes a checkpoint at
    ``checkpoint_fraction`` of the grid, lands the rest as a
    post-checkpoint suffix, then measures:

    - full index replay (no checkpoint) entries/s,
    - checkpointed restore (decode + suffix-only replay) entries/s,
    - restart-to-first-grant: EmbeddedCoordinator construction + start
      + one client.request() round trip on the recovered data dir
      (``hold_back`` tiles are left incomplete so a grant exists).
    """
    import tempfile

    from distributedmandelbrot_tpu.cli import parse_level_settings
    from distributedmandelbrot_tpu.coordinator import EmbeddedCoordinator
    from distributedmandelbrot_tpu.coordinator.recovery import (
        RecoveryManager, load_restore_state)
    from distributedmandelbrot_tpu.coordinator.scheduler import TileScheduler
    from distributedmandelbrot_tpu.core.chunk import Chunk
    from distributedmandelbrot_tpu.storage.store import ChunkStore
    from distributedmandelbrot_tpu.worker import DistributerClient

    settings = parse_level_settings(levels)
    grid = [(s.level, i, j) for s in settings
            for i in range(s.level) for j in range(s.level)]
    n_total = len(grid) - hold_back
    n_ckpt = int(n_total * checkpoint_fraction)

    out: dict = {"config": "recovery", "levels": levels,
                 "index_entries": n_total, "checkpoint_entries": n_ckpt,
                 "suffix_entries": n_total - n_ckpt}
    with tempfile.TemporaryDirectory() as tmp:
        store = ChunkStore(tmp)
        store.setup()
        for level, i, j in grid[:n_ckpt]:
            store.save(Chunk.never(level, i, j))
        # Index offset at "checkpoint time" — entries past it are the
        # suffix a checkpointed restore replays.
        ckpt_offset = store.index_offset()
        for level, i, j in grid[n_ckpt:n_total]:
            store.save(Chunk.never(level, i, j))

        def median_restore_s() -> float:
            times = []
            for _ in range(max(repeats, 2)):
                t0 = time.perf_counter()
                load_restore_state(store, settings)
                times.append(time.perf_counter() - t0)
            times.sort()
            return times[len(times) // 2]

        # Full replay baseline: no checkpoint exists yet, so restore
        # scans every entry.
        full = median_restore_s()
        out["full_replay_s"] = full
        out["full_replay_entries_per_s"] = n_total / full if full else 0.0

        # Checkpoint as if taken mid-run: the scheduler knows the first
        # n_ckpt tiles and the index offset recorded when they landed
        # (build() pairs offset and snapshot the same way live).
        completed = {k for k in grid[:n_ckpt]}
        sched = TileScheduler(settings, completed=completed)
        mgr = RecoveryManager(store, sched, generation=1)
        ckpt = mgr.build()
        ckpt.index_offset = ckpt_offset
        mgr.write(ckpt)
        suffix = median_restore_s()
        restored = load_restore_state(store, settings)
        out["suffix_replay_s"] = suffix
        out["suffix_replayed_entries"] = restored.replayed_entries
        out["suffix_replay_entries_per_s"] = \
            restored.replayed_entries / suffix if suffix else 0.0
        out["restore_used_checkpoint"] = restored.checkpoint is not None

        # Restart-to-first-grant on the recovered data dir.
        t0 = time.perf_counter()
        with EmbeddedCoordinator(tmp, settings, gateway=False,
                                 exporter=False) as co:
            w = DistributerClient("127.0.0.1", co.distributer_port).request()
            out["restart_to_first_grant_s"] = time.perf_counter() - t0
            out["first_grant_available"] = w is not None
    return out


def bench_storm(repeats: int, *, level: int = 8,
                crowd_phases: str = "steady:150x3,spike:900x3,steady:150x3",
                scale_phases: str = "steady:400x6",
                gateway_rate: float = 250.0,
                replica_rate: float = 150.0) -> dict:
    """Million-viewer read-path shape (no accelerator): an open-loop
    Poisson/Zipf storm against the serving tier.  Two legs:

    - flash crowd vs an embedded coordinator's gateway: a pre-seeded
      level grid, steady -> 6x spike -> steady, with the admission
      token bucket sized so ``QUERY_OVERLOADED`` engages during the
      spike and the recovery phase goes clean again;
    - replica scaling: the same storm against a 1- then 2-replica
      :class:`GatewayFleet` sharing one object store, tile cache off so
      every request pays admission — the goodput ratio is the
      horizontal-read headline.

    Open loop throughout: arrivals follow the schedule, never the
    server, so queue collapse shows up as shed fraction and tail
    latency instead of silently slowing the generator down.
    """
    import asyncio
    import tempfile

    from distributedmandelbrot_tpu import loadgen
    from distributedmandelbrot_tpu.cli import parse_level_settings
    from distributedmandelbrot_tpu.coordinator import EmbeddedCoordinator
    from distributedmandelbrot_tpu.core.chunk import Chunk
    from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
    from distributedmandelbrot_tpu.loadgen.driver import GatewayDriver
    from distributedmandelbrot_tpu.loadgen.replicas import GatewayFleet
    from distributedmandelbrot_tpu.storage.backends import (
        MemoryObjectStore, ObjectStoreBackend)
    from distributedmandelbrot_tpu.storage.store import ChunkStore

    # RLE-friendly pixels: every seeded tile's wire payload is ~1 KB, so
    # both legs measure admission + framing, not payload bandwidth.
    pixels = np.repeat(np.arange(64, dtype=np.uint8) + 1,
                       CHUNK_PIXELS // 64)
    grid = [(level, i, j) for i in range(level) for j in range(level)]

    def run_storm(addresses, phases, sampler) -> tuple[dict, list]:
        schedule = loadgen.build_schedule(phases, sampler, seed=0)
        driver = GatewayDriver(addresses, timeout=60.0)
        recorder = loadgen.StormRecorder()
        runner = loadgen.OpenLoopRunner(schedule, driver, recorder)
        duration = asyncio.run(runner.run())
        return recorder.report(
            duration=duration,
            offered=loadgen.schedule.offered_rate(schedule),
            phases=[p.name for p in phases]), phases

    # -- leg 1: flash crowd vs the embedded coordinator's gateway -----
    out: dict = {"config": "storm", "storm_level": level,
                 "storm_crowd_phases": crowd_phases,
                 "storm_gateway_rate": gateway_rate}
    with tempfile.TemporaryDirectory() as tmp:
        settings = parse_level_settings(f"{level}:100")
        seeder = ChunkStore(tmp)
        seeder.setup()
        for key in grid:
            seeder.save(Chunk(*key, pixels))
        with EmbeddedCoordinator(tmp, settings, exporter=False,
                                 gateway_cache_tiles=2,
                                 gateway_rate=gateway_rate,
                                 gateway_burst=50.0,
                                 gateway_max_queue_depth=256) as co:
            crowd, phases = run_storm(
                [("127.0.0.1", co.gateway_port)],
                loadgen.parse_phases(crowd_phases),
                loadgen.ZipfTiles(level, s=1.1, seed=0))
            out["storm_gateway_overloaded"] = \
                co.counters.get("gateway_overloaded")
    spike = crowd["phases"][phases[1].name]
    recovery = crowd["phases"][phases[2].name]
    out.update({
        "storm_requests": crowd["requests"],
        "storm_completed": crowd["completed"],
        "storm_shed": crowd["shed"],
        "storm_errors": crowd["errors"],
        "storm_offered_rate": crowd["offered_rate"],
        "storm_goodput": crowd["goodput"],
        "storm_shed_fraction": crowd["shed_fraction"],
        "storm_p50_s": crowd["p50"], "storm_p99_s": crowd["p99"],
        "storm_p999_s": crowd["p999"],
        "storm_spike_completed": spike["completed"],
        "storm_spike_shed": spike["shed"],
        "storm_recovery_completed": recovery["completed"],
        "storm_recovery_shed": recovery["shed"],
        # The admission-control story in one flag: sheds during the
        # spike, (near-)none once the crowd passes.
        "storm_overload_engaged": spike["shed"] > 0,
        "storm_overload_recovered":
            recovery["shed"] * 20 <= max(recovery["completed"], 1),
    })

    # -- leg 2: horizontal reads, 1 vs 2 replicas ---------------------
    kv = MemoryObjectStore()
    seeder = ChunkStore(backend=ObjectStoreBackend(kv))
    for key in grid:
        seeder.save(Chunk(*key, pixels))
    goodput: dict[int, float] = {}
    for replicas in (1, 2):
        with GatewayFleet(kv, replicas=replicas, cache_tiles=0,
                          rate=replica_rate, burst=15.0,
                          max_queue_depth=512) as fleet:
            report, _ = run_storm(
                fleet.addresses, loadgen.parse_phases(scale_phases),
                loadgen.ZipfTiles(level, s=0.05, seed=1))
        goodput[replicas] = report["goodput"]
        out[f"storm_goodput_{replicas}r"] = report["goodput"]
        out[f"storm_shed_fraction_{replicas}r"] = report["shed_fraction"]
    speedup = goodput[2] / goodput[1] if goodput[1] else 0.0
    out.update({
        "metric": f"loadgen storm: goodput scaling, 2 vs 1 gateway "
                  f"replicas over one object store "
                  f"(rate-bound at {replica_rate}/s per replica)",
        "value": round(speedup, 2), "unit": "x",
        "storm_scale_phases": scale_phases,
        "storm_replica_rate": replica_rate,
    })
    return out


def _depth_sensitive_tiles(level: int, full_depth: int, paint_depth: int,
                           count: int) -> list:
    """Pick ``count`` tiles of the ``level`` grid where full depth costs
    measurably more than a ``paint_depth`` first paint, without costing
    minutes.

    With per-pixel early exit (the native worker) a tile's compute cost
    is proportional to its mean ``min(escape_iter, depth)``, so the
    paint-vs-refine gap lives in tiles with a fat escape-time tail:
    mostly fast-escaping pixels plus a slow halo near the set boundary.
    Mostly-exterior tiles flatten by ~iter 20 (full depth costs the same
    as the paint) and interior-heavy ones never finish.  A low-res
    escape-time map (32x32 samples per tile, one vectorized pass over
    the whole domain) estimates both depths' mean cost per tile; tiles
    are ranked by the cost ratio within an affordability cap.
    """
    from distributedmandelbrot_tpu.core import geometry

    res = 32
    n = level * res
    step = (geometry.MAX_AXIS - geometry.MIN_AXIS) / n
    xs = geometry.MIN_AXIS + step * (np.arange(n) + 0.5)
    c = xs[None, :] + 1j * xs[:, None]  # row = imag, col = real
    z = np.zeros_like(c)
    alive = np.ones(c.shape, dtype=bool)
    # ~300 iterations separates the slow halo from true interior well
    # past the escape-time knee; deeper adds scan cost without moving
    # the ranking.
    cap = min(full_depth, 300)
    esc = np.full(c.shape, cap, dtype=np.int32)
    with np.errstate(over="ignore", invalid="ignore"):
        for it in range(cap):
            z = np.where(alive, z * z + c, z)
            out = alive & ((z.real * z.real + z.imag * z.imag) > 4.0)
            esc[out] = it + 1
            alive &= ~out

    def mean_iters(depth: int) -> np.ndarray:
        return np.minimum(esc, depth).astype(np.float64).reshape(
            level, res, level, res).mean(axis=(1, 3))

    m_paint = mean_iters(min(paint_depth, cap))
    m_full = mean_iters(cap)
    # m_full <= 80 mean iterations keeps one full-depth compute in the
    # low seconds on the native backend; the >= 4x ratio floor keeps the
    # paint-vs-depth gap above serve-path overheads (grid gen, save,
    # render, transfer).
    rows = sorted(
        (-(float(m_full[j, i]) / max(float(m_paint[j, i]), 1.0)), i, j)
        for i in range(level) for j in range(level)
        if m_full[j, i] <= 80.0
        and m_full[j, i] >= 4.0 * max(float(m_paint[j, i]), 1.0))
    if len(rows) < count:  # coarse grids: best available ratios
        rows = sorted(
            (-(float(m_full[j, i]) / max(float(m_paint[j, i]), 1.0)), i, j)
            for i in range(level) for j in range(level)
            if m_full[j, i] <= 80.0)
    return [(level, i, j) for _, i, j in rows[:count]]


def bench_sessions(repeats: int, *, level: int = 8, sessions: int = 8,
                   crowd_phases: str = "steady:120x2,spike:700x3,"
                                       "steady:120x2",
                   hot_share: float = 0.6, session_rate: float = 30.0,
                   session_burst: float = 30.0,
                   paint_levels: str = "32:300",
                   first_paint_iter: int = 24,
                   paint_tiles: int = 5) -> dict:
    """Interactive-session shape (no accelerator): the three numbers the
    sessions subsystem exists to move.  Two legs:

    - trajectory storm vs a session-enabled 2-replica fleet over a
      fully-seeded grid: panning sessions with a flash-crowd spike
      skewed ``hot_share`` onto one session.  Reports the prefetch hit
      ratio (predictor quality on real pans) and the per-session OK
      spread — with per-session token budgets the hot session is
      throttled instead of starving the rest, so the spread stays
      bounded;
    - first paint vs full depth on cold tiles: an embedded coordinator
      with a numpy worker farm, progressive refinement on.  A session
      query on a cold tile is served at ``first_paint_iter`` and
      refined to full depth behind the reply; a legacy render on an
      equally cold tile pays full depth up front.  The headline is the
      median latency ratio between the two.
    """
    import asyncio
    import tempfile
    import threading

    from distributedmandelbrot_tpu import loadgen
    from distributedmandelbrot_tpu.cli import parse_level_settings
    from distributedmandelbrot_tpu.coordinator import EmbeddedCoordinator
    from distributedmandelbrot_tpu.core.chunk import Chunk
    from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
    from distributedmandelbrot_tpu.loadgen.replicas import GatewayFleet
    from distributedmandelbrot_tpu.obs import names as obs_names
    from distributedmandelbrot_tpu.storage.backends import (
        MemoryObjectStore, ObjectStoreBackend)
    from distributedmandelbrot_tpu.storage.store import ChunkStore
    from distributedmandelbrot_tpu.viewer import DataClient, FetchStatus
    from distributedmandelbrot_tpu.worker import (DistributerClient,
                                                  NativeBackend,
                                                  NumpyBackend, Worker)

    out: dict = {"config": "sessions", "sessions_level": level,
                 "sessions_count": sessions,
                 "sessions_crowd_phases": crowd_phases,
                 "sessions_hot_share": hot_share,
                 "sessions_rate": session_rate}

    # -- leg 1: trajectory storm, prefetch + fairness -----------------
    pixels = np.repeat(np.arange(64, dtype=np.uint8) + 1,
                       CHUNK_PIXELS // 64)
    kv = MemoryObjectStore()
    seeder = ChunkStore(backend=ObjectStoreBackend(kv))
    for i in range(level):
        for j in range(level):
            seeder.save(Chunk(level, i, j, pixels))
    phases = loadgen.parse_phases(crowd_phases)
    schedule = loadgen.build_session_schedule(
        phases, level=level, sessions=sessions, seed=0,
        hot_share=hot_share)
    with GatewayFleet(kv, replicas=2, sessions=True,
                      session_rate=session_rate,
                      session_burst=session_burst) as fleet:
        driver = loadgen.SessionDriver(fleet.addresses, timeout=60.0)
        recorder = loadgen.StormRecorder()
        runner = loadgen.SessionRunner(schedule, driver, recorder)
        duration = asyncio.run(runner.run())
        report = recorder.report(
            duration=duration,
            offered=loadgen.schedule.offered_rate(schedule),
            phases=[p.name for p in phases])
        hits = fleet.counter(obs_names.PREFETCH_HITS)
        misses = fleet.counter(obs_names.PREFETCH_MISSES)
        out["sessions_opened"] = fleet.counter(obs_names.SESSION_OPENS)
        out["sessions_throttled"] = fleet.counter(
            obs_names.SESSION_THROTTLED)
        out["prefetch_planned"] = fleet.counter(
            obs_names.PREFETCH_PLANNED)
        out["prefetch_warmed"] = fleet.counter(obs_names.PREFETCH_WARMED)
    ok_min, ok_max = loadgen.ok_spread(driver.ok_by_session, sessions)
    out.update({
        "sessions_requests": report["requests"],
        "sessions_completed": report["completed"],
        "sessions_shed": report["shed"],
        "sessions_errors": report["errors"],
        "sessions_goodput": report["goodput"],
        "sessions_p50_s": report["p50"], "sessions_p99_s": report["p99"],
        "prefetch_hits": hits, "prefetch_misses": misses,
        "prefetch_hit_ratio":
            round(hits / (hits + misses), 4) if hits + misses else 0.0,
        "sessions_ok_min": ok_min, "sessions_ok_max": ok_max,
        # Bounded-spread flag: the hot session may only beat the
        # quietest by what its token budget allows, not by its offered
        # share of the storm.
        "sessions_spread": round(ok_max / max(ok_min, 1), 2),
        "sessions_fair_bounded": ok_max <= 5 * max(ok_min, 1),
    })

    # -- leg 2: first paint vs full depth on cold tiles ---------------
    settings = parse_level_settings(paint_levels)
    paint_level = settings[0].level
    full_iter = settings[0].max_iter
    # Only boundary-straddling tiles make the comparison meaningful:
    # mostly-exterior tiles flatten by ~iter 20 (full depth costs the
    # same as the paint) and mostly-interior ones cost minutes per
    # compute.  Interleave the picks so neither measure gets
    # systematically cheaper tiles than the other.
    picks = _depth_sensitive_tiles(paint_level, full_iter,
                                   first_paint_iter, 2 * paint_tiles)
    session_tiles = picks[0::2][:paint_tiles]
    legacy_tiles = picks[1::2][:paint_tiles]
    with tempfile.TemporaryDirectory() as tmp, \
            EmbeddedCoordinator(tmp, settings, exporter=False,
                                first_paint_max_iter=first_paint_iter,
                                ondemand_deadline=120.0,
                                ondemand_poll_interval=0.1) as co:
        # Pre-complete the whole grid with no bytes behind it.  The
        # background frontier farm would otherwise wedge the single
        # worker on a near-interior tile for minutes; instead the farm
        # idles and every measured fetch rides the on-demand heal path
        # (completed-but-missing -> un-complete + re-grant), so both
        # measures pay the identical path against an idle worker.
        while (w := co.scheduler.acquire()) is not None:
            co.scheduler.complete(w)
        stop = threading.Event()
        # Per-pixel early exit makes tile cost track mean escape work —
        # the model the tile picker ranks by; the numpy golden pays per
        # iteration regardless of how many pixels are still active, so
        # it is the fallback, not the default.
        try:
            backend = NativeBackend()
        except RuntimeError:
            backend = NumpyBackend()
        worker = Worker(
            DistributerClient("127.0.0.1", co.distributer_port),
            backend, overlap_io=False)
        wt = threading.Thread(target=worker.run_forever,
                              kwargs=dict(poll_interval=0.02, stop=stop),
                              daemon=True)
        wt.start()
        try:
            client = DataClient("127.0.0.1", co.gateway_port,
                                timeout=600)
            first_paint_lat = []
            for key in session_tiles:
                t0 = time.perf_counter()
                _, status = client.fetch_session(*key)
                first_paint_lat.append(time.perf_counter() - t0)
                assert status is FetchStatus.OK, status
                # Drain the refine before the next paint: on one worker
                # the deep recompute sits at the frontier head and would
                # otherwise queue ahead of the next first paint,
                # contaminating its latency with full-depth compute.
                target = co.counters.get(
                    obs_names.SESSION_REFINES_SCHEDULED)
                deadline = time.monotonic() + 120.0
                while time.monotonic() < deadline and \
                        co.counters.get(
                            obs_names.SESSION_REFINES_COMPLETED) < target:
                    time.sleep(0.05)
            full_depth_lat = []
            for key in legacy_tiles:
                t0 = time.perf_counter()
                _, status = client.fetch_render(*key)
                full_depth_lat.append(time.perf_counter() - t0)
                assert status is FetchStatus.OK, status
            client.close()
            first_paints = co.counters.get(obs_names.SESSION_FIRST_PAINTS)
            # Refinement closes the loop in the background: wait for the
            # deep variants of the painted tiles to land and invalidate
            # the shallow cache entries.
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                if co.counters.get(obs_names.SESSION_REFINES_COMPLETED) \
                        >= first_paints:
                    break
                time.sleep(0.05)
        finally:
            stop.set()
            wt.join(timeout=60)
        cc = co.counters.snapshot()
    first_paint_lat.sort()
    full_depth_lat.sort()
    fp_p50 = first_paint_lat[len(first_paint_lat) // 2]
    fd_p50 = full_depth_lat[len(full_depth_lat) // 2]
    out.update({
        "paint_levels": paint_levels,
        "first_paint_iter": first_paint_iter,
        "full_depth_iter": full_iter,
        "first_paint_p50_s": round(fp_p50, 4),
        "full_depth_p50_s": round(fd_p50, 4),
        "session_first_paints": first_paints,
        "session_refines_scheduled":
            cc.get(obs_names.SESSION_REFINES_SCHEDULED, 0),
        "session_refines_completed":
            cc.get(obs_names.SESSION_REFINES_COMPLETED, 0),
        "tile_cache_invalidations":
            cc.get(obs_names.TILE_CACHE_INVALIDATIONS, 0),
        "metric": f"interactive sessions: cold-tile first paint "
                  f"(iter {first_paint_iter}) vs full depth "
                  f"(iter {full_iter}) median latency",
        "value": round(fd_p50 / fp_p50, 2) if fp_p50 else 0.0,
        "unit": "x",
    })
    return out


def bench_shards(repeats: int, *, levels: str = "64:100",
                 shard_counts: tuple = (1, 2, 4), clients: int = 4,
                 duration: float = 4.0, batch: int = 32) -> dict:
    """Sharded control-plane scaling (no accelerator): aggregate lease-
    grant throughput as the coordinator fleet grows 1 -> 2 -> 4 shards,
    plus restart-to-first-grant under live load.

    Each leg spawns N ``ShardedCoordinator`` subprocesses (one event
    loop per shard — subprocesses, not threads, so the GIL never
    serializes the fleet) over a shared data dir with near-zero lease
    timeouts, so the owned frontier recycles continuously; ``clients``
    grant-storm subprocesses (chaos/driver.py ``drain`` role) then
    hammer multi-homed REQN exchanges for ``duration`` seconds without
    ever uploading.  Aggregate grants/s is total grants over the
    slowest client's window — a pure grant-path number, uncontaminated
    by compute or persistence.  ``cpu_count`` rides along because the
    curve is only meaningful with at least one core per shard: on a
    1-core box every process time-slices and the ratio pins near 1x.

    The restart leg re-runs the widest storm, SIGKILLs shard 0
    mid-storm, respawns it on fresh ephemeral ports (ring.json
    rewritten in place — ownership never moves), and reports the time
    from respawn to that shard's first post-restart grant (polled from
    its /varz), while the storm clients re-dial around the hole.
    """
    import os
    import subprocess
    import tempfile
    import urllib.request

    repo_root = os.path.dirname(os.path.abspath(__file__))
    driver = "distributedmandelbrot_tpu.chaos.driver"

    def spawn_shard(tmp: str, leg: str, k: int, n: int
                    ) -> tuple[subprocess.Popen, str]:
        port_file = os.path.join(tmp, f"{leg}-ports-{k}.json")
        if os.path.exists(port_file):
            os.unlink(port_file)
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep \
            + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", driver, "shard",
             os.path.join(tmp, f"farm-{leg}"), port_file, levels,
             str(k), str(n),
             "--lease-timeout", "0.05", "--sweep-period", "0.02",
             "--checkpoint-period", "0"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        return proc, port_file

    def read_ports(proc: subprocess.Popen, port_file: str) -> dict:
        deadline = time.monotonic() + 30.0
        while not os.path.exists(port_file):
            if proc.poll() is not None:
                raise RuntimeError(
                    f"shard died during startup (exit {proc.returncode})")
            if time.monotonic() > deadline:
                raise RuntimeError("shard never wrote its port file")
            time.sleep(0.05)
        with open(port_file, "r", encoding="utf-8") as f:
            return json.load(f)

    def write_ring(tmp: str, leg: str, infos: list[dict]) -> str:
        from distributedmandelbrot_tpu.control.ring import (HashRing,
                                                            ShardInfo)
        path = os.path.join(tmp, f"ring-{leg}.json")
        HashRing([ShardInfo("127.0.0.1",
                            distributer_port=i["distributer"],
                            dataserver_port=i["dataserver"])
                  for i in infos], version=1).save(path)
        return path

    def storm(tmp: str, leg: str, ring_path: str, secs: float
              ) -> tuple[int, float]:
        """clients x drain subprocesses; (total grants, slowest window)."""
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep \
            + env.get("PYTHONPATH", "")
        outs, procs = [], []
        for c in range(clients):
            out = os.path.join(tmp, f"{leg}-drain-{c}.json")
            outs.append(out)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", driver, "drain", ring_path,
                 "--duration", str(secs), "--batch", str(batch),
                 "--out", out],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL))
        grants, slowest = 0, 0.0
        for proc, out in zip(procs, outs):
            proc.wait(timeout=secs + 60.0)
            with open(out, "r", encoding="utf-8") as f:
                rep = json.load(f)
            grants += rep["grants"]
            slowest = max(slowest, rep["seconds"])
        return grants, slowest

    out: dict = {"config": "shards", "levels": levels, "clients": clients,
                 "duration_s": duration, "batch": batch,
                 "cpu_count": os.cpu_count(),
                 "grants_per_s": {}, "grants": {}}
    with tempfile.TemporaryDirectory(prefix="dmtpu-shardbench-") as tmp:
        for n in shard_counts:
            leg = f"n{n}"
            shards = [spawn_shard(tmp, leg, k, n) for k in range(n)]
            try:
                infos = [read_ports(p, f) for p, f in shards]
                ring_path = write_ring(tmp, leg, infos)
                grants, slowest = storm(tmp, leg, ring_path, duration)
            finally:
                for proc, _ in shards:
                    proc.kill()
                    proc.wait()
            out["grants"][str(n)] = grants
            out["grants_per_s"][str(n)] = \
                round(grants / slowest, 1) if slowest else 0.0
        first = str(shard_counts[0])
        last = str(shard_counts[-1])
        base = out["grants_per_s"][first]
        out[f"scaling_{last}v{first}"] = \
            round(out["grants_per_s"][last] / base, 2) if base else 0.0

        # Restart-to-first-grant under live load: widest fleet, kill
        # shard 0 two seconds into a longer storm, bring it back on
        # fresh ports, poll its /varz for the first post-restart grant.
        n = shard_counts[-1]
        leg = "restart"
        shards = [spawn_shard(tmp, leg, k, n) for k in range(n)]
        try:
            infos = [read_ports(p, f) for p, f in shards]
            ring_path = write_ring(tmp, leg, infos)
            env = dict(os.environ)
            env["PYTHONPATH"] = repo_root + os.pathsep \
                + env.get("PYTHONPATH", "")
            storm_secs = duration + 8.0
            drains = [subprocess.Popen(
                [sys.executable, "-m", driver, "drain", ring_path,
                 "--duration", str(storm_secs), "--batch", str(batch),
                 "--out", os.path.join(tmp, f"restart-drain-{c}.json")],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL) for c in range(clients)]
            time.sleep(2.0)
            victim, _ = shards[0]
            victim.kill()
            victim.wait()
            t_respawn = time.monotonic()
            shards[0] = spawn_shard(tmp, leg, 0, n)
            infos[0] = read_ports(*shards[0])
            write_ring(tmp, leg, infos)  # same version: only ports moved
            blip = None
            poll_deadline = time.monotonic() + 60.0
            while time.monotonic() < poll_deadline:
                try:
                    with urllib.request.urlopen(
                            "http://127.0.0.1:%d/varz"
                            % infos[0]["exporter"], timeout=0.5) as resp:
                        varz = json.loads(resp.read().decode("utf-8"))
                    granted = sum(
                        v for label, v in varz.get("counters", {}).items()
                        if label.split("{")[0] == "workloads_granted")
                    if granted > 0:
                        blip = round(time.monotonic() - t_respawn, 3)
                        break
                except OSError:
                    pass
                time.sleep(0.05)
            out["restart_to_first_grant_s"] = blip
            for proc in drains:
                proc.wait(timeout=storm_secs + 60.0)
        finally:
            for proc, _ in shards:
                proc.kill()
                proc.wait()
    return out


def bench_obs(repeats: int, *, levels: str = "64:100", n_shards: int = 2,
              clients: int = 2, duration: float = 3.0, batch: int = 32,
              scrape_period: float = 2.0) -> dict:
    """Observability overhead (no accelerator): grant-path throughput
    of a 2-shard farm under grant storm, measured bare vs with the full
    fleet plane attached — a FleetAggregator pulling every shard's
    ``/varz`` + ``/timeseries`` and merging ``snapshot()`` at the
    deployment-default scrape period.  The shards run their samplers
    and SLO loops in BOTH legs (they are on whenever an exporter is),
    so the delta isolates what watching a farm costs the farm: serving
    scrapes.

    A third leg per repeat runs the same storm with the flight recorder
    disabled (``DMTPU_FLIGHT=0``): the bare leg already records flight
    events on every grant (the recorder is on whenever a coordinator
    is), so bare-vs-flight-off isolates what the black box costs the
    grant path.  Gate: ``flight_overhead_pct < 1``.

    Per repeat the legs run back-to-back on fresh subprocess fleets;
    the reported rates are each leg's best repeat (the storm numbers
    are noisy on shared CI boxes, and overhead is a property of the
    fast path, not of scheduler jitter).  Note ``cpu_count``: on a
    1-core box the aggregator thread time-slices against the very
    storm it watches, so the measured delta is an upper bound on real
    fleet overhead.  The acceptance gate is ``overhead_pct < 1``.
    """
    import os
    import subprocess
    import tempfile
    import threading

    from distributedmandelbrot_tpu.obs.fleet import FleetAggregator

    repo_root = os.path.dirname(os.path.abspath(__file__))
    driver = "distributedmandelbrot_tpu.chaos.driver"

    def _env(flight: bool = True) -> dict:
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep \
            + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        if not flight:
            env["DMTPU_FLIGHT"] = "0"
        return env

    def spawn_shard(tmp: str, leg: str, k: int, *, flight: bool = True
                    ) -> tuple[subprocess.Popen, str]:
        port_file = os.path.join(tmp, f"{leg}-ports-{k}.json")
        proc = subprocess.Popen(
            [sys.executable, "-m", driver, "shard",
             os.path.join(tmp, f"farm-{leg}"), port_file, levels,
             str(k), str(n_shards),
             "--lease-timeout", "0.05", "--sweep-period", "0.02",
             "--checkpoint-period", "0"],
            env=_env(flight), stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        return proc, port_file

    def read_ports(proc: subprocess.Popen, port_file: str) -> dict:
        deadline = time.monotonic() + 30.0
        while not os.path.exists(port_file):
            if proc.poll() is not None:
                raise RuntimeError(
                    f"shard died during startup (exit {proc.returncode})")
            if time.monotonic() > deadline:
                raise RuntimeError("shard never wrote its port file")
            time.sleep(0.05)
        with open(port_file, "r", encoding="utf-8") as f:
            return json.load(f)

    def run_leg(tmp: str, leg: str, observed: bool, *,
                flight: bool = True) -> tuple[float, int, dict]:
        from distributedmandelbrot_tpu.control.ring import (HashRing,
                                                            ShardInfo)
        shards = [spawn_shard(tmp, leg, k, flight=flight)
                  for k in range(n_shards)]
        scrapes = [0]
        stop = threading.Event()
        scraper = None
        snap: dict = {}
        try:
            infos = [read_ports(p, f) for p, f in shards]
            ring_path = os.path.join(tmp, f"ring-{leg}.json")
            HashRing([ShardInfo("127.0.0.1",
                                distributer_port=i["distributer"],
                                dataserver_port=i["dataserver"],
                                exporter_port=i["exporter"])
                      for i in infos], version=1).save(ring_path)
            agg = None
            if observed:
                agg = FleetAggregator(
                    [f"shard@127.0.0.1:{i['exporter']}" for i in infos],
                    rate_window=30.0, timeout=1.0)

                def _scrape_loop() -> None:
                    while not stop.is_set():
                        agg.scrape_once()
                        agg.snapshot()
                        scrapes[0] += 1
                        stop.wait(scrape_period)

                scraper = threading.Thread(target=_scrape_loop,
                                           daemon=True)
                scraper.start()
            outs, procs = [], []
            for c in range(clients):
                out_path = os.path.join(tmp, f"{leg}-drain-{c}.json")
                outs.append(out_path)
                procs.append(subprocess.Popen(
                    [sys.executable, "-m", driver, "drain", ring_path,
                     "--duration", str(duration), "--batch", str(batch),
                     "--out", out_path],
                    env=_env(), stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL))
            grants, slowest = 0, 0.0
            for proc, out_path in zip(procs, outs):
                proc.wait(timeout=duration + 60.0)
                with open(out_path, "r", encoding="utf-8") as f:
                    rep = json.load(f)
                grants += rep["grants"]
                slowest = max(slowest, rep["seconds"])
            if agg is not None:
                snap = agg.snapshot()
        finally:
            stop.set()
            if scraper is not None:
                scraper.join(timeout=10.0)
            for proc, _ in shards:
                proc.kill()
                proc.wait()
        return (grants / slowest if slowest else 0.0), scrapes[0], snap

    out: dict = {"config": "obs", "levels": levels, "n_shards": n_shards,
                 "clients": clients, "duration_s": duration,
                 "scrape_period_s": scrape_period,
                 "cpu_count": os.cpu_count(), "repeats": repeats}
    base_rates, observed_rates, scrape_counts = [], [], []
    flight_off_rates = []
    last_snap: dict = {}
    with tempfile.TemporaryDirectory(prefix="dmtpu-obsbench-") as tmp:
        for r in range(repeats):
            rate, _, _ = run_leg(tmp, f"fl0{r}", observed=False,
                                 flight=False)
            flight_off_rates.append(rate)
            rate, _, _ = run_leg(tmp, f"base{r}", observed=False)
            base_rates.append(rate)
            rate, n_scrapes, snap = run_leg(tmp, f"obs{r}", observed=True)
            observed_rates.append(rate)
            scrape_counts.append(n_scrapes)
            if snap:
                last_snap = snap
    base = max(base_rates)
    observed = max(observed_rates)
    flight_off = max(flight_off_rates)
    overhead = (base - observed) / base * 100.0 if base else 0.0
    # The bare leg IS the flight-on leg (the recorder rides every
    # coordinator); off-vs-on isolates the note() cost on grants.
    fl_overhead = (flight_off - base) / flight_off * 100.0 \
        if flight_off else 0.0
    out["grants_per_s_bare"] = round(base, 1)
    out["grants_per_s_observed"] = round(observed, 1)
    out["grants_per_s_flight_off"] = round(flight_off, 1)
    out["grants_per_s_flight_on"] = round(base, 1)
    out["scrapes_per_leg"] = scrape_counts
    out["overhead_pct"] = round(overhead, 2)
    out["overhead_under_1pct"] = overhead < 1.0
    out["flight_overhead_pct"] = round(fl_overhead, 2)
    out["flight_overhead_under_1pct"] = fl_overhead < 1.0
    out["fleet_totals"] = last_snap.get("totals", {})
    out["fleet_roles"] = {role: doc.get("healthy", 0)
                          for role, doc in
                          (last_snap.get("roles") or {}).items()}
    return out


def _ensure_live_backend(probe_timeout: float = 120.0) -> bool:
    """Guard against a dead accelerator tunnel: on this rig the TPU is
    reached through a network tunnel whose failure mode is jax backend
    init hanging FOREVER (no error).  Probe device init in a subprocess
    with a deadline; if it doesn't come up, force the CPU platform (with
    a virtual 8-device mesh) in this process so the bench still emits
    its JSON line instead of hanging the driver."""
    import os

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from __graft_entry__ import _force_cpu_mesh, backend_alive

    if backend_alive(probe_timeout):
        return False
    print("# accelerator backend unreachable; falling back to CPU "
          "(virtual 8-device mesh)", file=sys.stderr)
    _force_cpu_mesh(8)
    return True


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--tile", type=int, default=1024)
    # 256 tiles = the fused megakernel's canonical batch: one dispatch
    # constant amortized over 268 Mpix (the 64-tile batch of BENCH_r05
    # and earlier could not bench past ~600 Mpix/s no matter how fast
    # the kernel, because a ~70 ms call constant dominated 67 Mpix).
    parser.add_argument("--tiles", type=int, default=256)
    parser.add_argument("--max-iter", type=int, default=1000)
    parser.add_argument("--dtype", choices=["f32", "f64"], default="f32")
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--segment", type=int, default=256)
    parser.add_argument("--all", action="store_true",
                        help="run the 5 BASELINE.md configs plus the farm "
                             "config (one JSON line each) instead of the "
                             "headline metric")
    parser.add_argument("--farm", action="store_true",
                        help="run only the production-shape farm config")
    parser.add_argument("--farm-backend", default="auto",
                        choices=["auto", "jax", "pallas", "numpy", "native",
                                 "mesh"],
                        help="compute backend for the farm config; 'native' "
                             "is the no-device control that isolates "
                             "framework overhead from tunnel/device cost")
    parser.add_argument("--farm-window", type=int, default=8,
                        help="pipelined-executor window for the farm "
                             "config (tiles in flight across all four "
                             "stages); 0 = legacy two-stage overlap — "
                             "the control leg for the pipelining delta")
    parser.add_argument("--farm-depth", type=int, default=2,
                        help="kernels in flight per device for the farm "
                             "config's pipelined executor")
    parser.add_argument("--farm-workers", type=int, default=0,
                        help="run the farm config with N worker "
                             "subprocesses against one coordinator "
                             "(aggregate Mpix/s + per-worker wire/lane "
                             "metrics); 0 = single in-process worker")
    parser.add_argument("--farm-lanes", type=int, default=0,
                        help="parallel upload lanes per worker for the "
                             "farm config (0 = one per local device, "
                             "capped at 4)")
    parser.add_argument("--farm-grant-batch", type=int, default=0,
                        help="batched lease grants per session round "
                             "trip for the farm config (0 = auto-size "
                             "to batch-tiles x devices)")
    parser.add_argument("--serve", action="store_true",
                        help="run only the serving-gateway config "
                             "(cold-miss, warm-hit, coalesced-storm)")
    parser.add_argument("--worst", action="store_true",
                        help="run only the worst-case boundary-view config "
                             "(raw vs shortcut per view)")
    parser.add_argument("--kernel-batch", metavar="KS", default="",
                        help="sweep the megakernel fusion width: "
                             "comma-separated K values (e.g. "
                             "'1,16,64,256'); one latency-decomposed "
                             "row per K at --tile/--max-iter")
    parser.add_argument("--mesh", action="store_true",
                        help="run the mesh megakernel worker leg: "
                             "devices x K scaling rows of the shard_map "
                             "fused launch (K values from --kernel-batch "
                             "when given, else 1,8,64) plus an "
                             "end-to-end dispatch_many worker row")
    parser.add_argument("--mesh-devices", type=int, default=0,
                        metavar="N",
                        help="force an N-device virtual CPU platform "
                             "before jax initializes (dev rigs without "
                             "a multi-chip accelerator; rows are marked "
                             "cpu_fallback)")
    parser.add_argument("--tileshape", action="store_true",
                        help="run only the 4096^2-vs-1024^2 production "
                             "tile-shape config (latency-decomposed)")
    parser.add_argument("--deep-slow", action="store_true",
                        help="run only the slow-dynamics deep-zoom config "
                             "(parabolic bond point; value = the default "
                             "auto-probed path, with exact-scan and "
                             "forced-BLA reference legs)")
    parser.add_argument("--recovery", action="store_true",
                        help="run only the crash-recovery config "
                             "(restart-to-first-grant latency, full vs "
                             "checkpoint+suffix index replay throughput; "
                             "no accelerator needed)")
    parser.add_argument("--storm", action="store_true",
                        help="run only the loadgen storm config "
                             "(open-loop flash crowd vs the gateway: "
                             "p50/p99/p999, goodput vs offered, shed "
                             "fraction, 1-vs-2-replica goodput scaling; "
                             "no accelerator needed)")
    parser.add_argument("--shards", action="store_true",
                        help="run only the sharded control-plane config "
                             "(aggregate grant throughput at 1/2/4 "
                             "coordinator shards, restart-to-first-grant "
                             "under live load; no accelerator needed)")
    parser.add_argument("--obs", action="store_true",
                        help="run only the observability-overhead config "
                             "(grant throughput bare vs under aggressive "
                             "fleet scraping; gate: overhead < 1%%; no "
                             "accelerator needed)")
    parser.add_argument("--sessions", action="store_true",
                        help="run only the interactive-sessions config "
                             "(trajectory storm: prefetch hit ratio + "
                             "per-session fairness spread; cold-tile "
                             "first-paint vs full-depth latency with a "
                             "numpy farm; no accelerator needed)")
    args = parser.parse_args()
    if args.obs:
        # Grant path + HTTP scrape plane only — no accelerator probe.
        print(json.dumps(bench_obs(args.repeats)), flush=True)
        return 0
    if args.sessions:
        # Session wire + numpy farm only — no accelerator probe.
        print(json.dumps(bench_sessions(args.repeats)), flush=True)
        return 0
    if args.shards:
        # Grant-path only — shard subprocesses + drain clients, no
        # compute, no accelerator probe.
        print(json.dumps(bench_shards(args.repeats)), flush=True)
        return 0
    if args.recovery:
        # Pure coordinator/storage path — skip the accelerator probe
        # entirely so this leg runs anywhere (CI, laptops, dead tunnels).
        print(json.dumps(bench_recovery(args.repeats)), flush=True)
        return 0
    if args.storm:
        # Read path over pre-seeded tiles — equally accelerator-free.
        print(json.dumps(bench_storm(args.repeats)), flush=True)
        return 0
    if args.kernel_batch or args.mesh:
        # jax-free smoke: these two legs stay drivable on CI lanes with
        # no jax at all (arg parsing + JSON shape verified against the
        # numpy single-tile fallback), without touching the backend
        # probe below, whose fallback path still imports jax.
        try:
            import jax  # noqa: F401  (probe only; backends init later)
        except ImportError:
            ks = [int(s) for s in args.kernel_batch.split(",")
                  if s.strip()] or [1]
            if args.kernel_batch:
                print(json.dumps(_bench_numpy_fallback(
                    args.tile, args.max_iter, ks,
                    f"megakernel fusion-width sweep ({args.tile}^2, "
                    f"max_iter={args.max_iter}, seahorse valley)")),
                    flush=True)
            if args.mesh:
                print(json.dumps(_bench_numpy_fallback(
                    args.tile, args.max_iter, ks,
                    f"mesh megakernel devices x K scaling "
                    f"({args.tile}^2, max_iter={args.max_iter}, "
                    f"seahorse valley)")), flush=True)
            return 0
    if args.mesh_devices:
        # Virtual multi-device CPU platform, carved before any backend
        # initializes — same mechanism as the dead-tunnel fallback, but
        # at the requested width.
        import os
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from __graft_entry__ import _force_cpu_mesh
        _force_cpu_mesh(args.mesh_devices)
        fell_back = True
    else:
        fell_back = _ensure_live_backend()

    def emit(result: dict) -> None:
        if fell_back:
            # Machine-readable marker: these are NOT accelerator numbers.
            result["cpu_fallback"] = True
        print(json.dumps(result), flush=True)

    if args.farm:
        if args.farm_workers > 0:
            emit(bench_farm_multi(args.repeats, workers=args.farm_workers,
                                  backend_name=args.farm_backend,
                                  window=args.farm_window,
                                  depth=args.farm_depth,
                                  upload_lanes=args.farm_lanes,
                                  grant_batch=args.farm_grant_batch))
        else:
            emit(bench_farm(args.repeats, backend_name=args.farm_backend,
                            window=args.farm_window, depth=args.farm_depth,
                            upload_lanes=args.farm_lanes,
                            grant_batch=args.farm_grant_batch))
        return 0

    if args.serve:
        emit(bench_serve(args.repeats, backend_name=args.farm_backend))
        return 0

    if args.worst:
        emit(bench_worstcase(args.repeats))
        return 0

    if args.kernel_batch or args.mesh:
        ks = [int(s) for s in args.kernel_batch.split(",")
              if s.strip()]
        if args.kernel_batch:
            emit(bench_kernel_batch(args.tile, args.max_iter,
                                    args.repeats, ks))
        if args.mesh:
            emit(bench_mesh(args.tile, args.max_iter, args.repeats,
                            ks or [1, 8, 64]))
        return 0

    if args.tileshape:
        emit(bench_tileshape(args.repeats))
        return 0

    if args.deep_slow:
        emit(bench_deepslow(args.repeats))
        return 0

    if args.all:
        failed = 0
        for fn in (bench_config1,
                   lambda r: bench_config2(r, args.segment),
                   lambda r: bench_config3(r, args.segment),
                   bench_config4,
                   lambda r: bench_config5(r, args.segment),
                   bench_deepslow,
                   bench_worstcase,
                   bench_tileshape,
                   bench_farm,
                   bench_serve):
            try:
                emit(fn(args.repeats))
            except Exception as e:  # finish the sweep, but fail the run
                failed += 1
                print(f"# config failed: {type(e).__name__}: {e}",
                      file=sys.stderr)
        return 1 if failed else 0

    result = bench_throughput(args.tile, args.tiles, args.max_iter,
                              args.dtype, args.repeats, args.segment)
    emit(result)
    return 0


if __name__ == "__main__":
    sys.exit(main())
