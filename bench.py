"""Benchmark harness: prints ONE JSON line with the headline metric.

Headline: escape-time throughput in Mpixels/s at max_iter=1000 on the
seahorse-valley zoom (BASELINE.md config 2 view), computed through the
production sharded path (device-side grids, batched tiles over the local
mesh).  ``vs_baseline`` is measured against the driver's north star of
500 Mpix/s (BASELINE.json) — set for a TPU v2-8; single-chip runs are
reported as-is.

Usage: python bench.py [--tile 1024] [--tiles N] [--max-iter 1000]
                       [--dtype f32] [--repeats 3] [--all]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

NORTH_STAR_MPIX_S = 500.0

# Seahorse valley: boundary-dense, iteration-heavy — a conservative view
# (full-view tiles with fast escapes bench much higher).
SEAHORSE = (-0.748, 0.09)


def _mesh_and_kernel():
    import jax

    from distributedmandelbrot_tpu.parallel import (batched_escape_pixels,
                                                    tile_mesh)
    mesh = tile_mesh()
    return jax, mesh, batched_escape_pixels


def _bench_params(tile: int, tiles: int):
    # One batch = `tiles` sub-tiles of the seahorse window, tiled spatially.
    span = 0.005
    params = np.empty((tiles, 3))
    for i in range(tiles):
        params[i] = (SEAHORSE[0] + (i % 4) * span,
                     SEAHORSE[1] + (i // 4) * span,
                     span / (tile - 1))
    return params


def _time_best(run, repeats: int) -> float:
    run()  # warmup/compile
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    return min(times)


def bench_throughput(tile: int, tiles: int, max_iter: int, dtype: str,
                     repeats: int, segment: int = 256) -> dict:
    """Fastest of the available compute paths (XLA sharded; Pallas on TPU)."""
    jax, mesh, batched_escape_pixels = _mesh_and_kernel()
    np_dtype = {"f32": np.float32, "f64": np.float64}[dtype]
    n_dev = mesh.devices.size
    params = _bench_params(tile, tiles)
    mrds = np.full(tiles, max_iter, dtype=np.int64)
    pixels = tiles * tile * tile

    results: dict[str, float] = {}

    def xla_run():
        return batched_escape_pixels(mesh, params, mrds, definition=tile,
                                     dtype=np_dtype, segment=segment)

    results["xla"] = pixels / _time_best(xla_run, repeats) / 1e6

    if dtype == "f32":
        try:  # Pallas path: block-granular early exit; TPU only.
            from distributedmandelbrot_tpu.core.geometry import TileSpec
            from distributedmandelbrot_tpu.ops.pallas_escape import (
                compute_tile_pallas, pallas_available)
            if pallas_available():
                specs = [TileSpec(p[0], p[1], p[2] * (tile - 1),
                                  p[2] * (tile - 1), tile, tile)
                         for p in params]

                def pallas_run():
                    for s in specs:
                        compute_tile_pallas(s, max_iter)

                results["pallas"] = \
                    pixels / _time_best(pallas_run, repeats) / 1e6
        except Exception as e:  # never let an experimental path kill bench
            print(f"# pallas path skipped: {type(e).__name__}: {e}",
                  file=sys.stderr)

    path, mpix_s = max(results.items(), key=lambda kv: kv[1])
    return {
        "metric": f"Mpixels/s @ max_iter={max_iter} "
                  f"({tiles}x{tile}^2 {dtype}, seahorse valley, "
                  f"{n_dev} {jax.devices()[0].platform} device(s), "
                  f"{path} path)",
        "value": round(mpix_s, 2),
        "unit": "Mpix/s",
        "vs_baseline": round(mpix_s / NORTH_STAR_MPIX_S, 4),
    }


def _mpix(pixels: int, seconds: float) -> float:
    return pixels / seconds / 1e6


def bench_config1(repeats: int) -> dict:
    """BASELINE config 1: 256^2, max_iter=256, full view, CPU reference path."""
    from distributedmandelbrot_tpu.core.geometry import TileSpec
    from distributedmandelbrot_tpu.ops import reference as ref

    spec = TileSpec(-2.0, -1.25, 2.5, 2.5, width=256, height=256)
    cr, ci = spec.grid_2d()

    def run():
        ref.scale_counts_to_uint8(ref.escape_counts(cr, ci, 256), 256)

    v = _mpix(256 * 256, _time_best(run, repeats))
    return {"metric": "config1 CPU-reference 256^2 mi=256 full view",
            "value": round(v, 2), "unit": "Mpix/s"}


def bench_config2(repeats: int, segment: int) -> dict:
    """BASELINE config 2: 1024^2, max_iter=1000, seahorse, one device."""
    from distributedmandelbrot_tpu.core.geometry import TileSpec
    from distributedmandelbrot_tpu.ops import compute_tile
    span = 0.005
    spec = TileSpec(SEAHORSE[0], SEAHORSE[1], span, span,
                    width=1024, height=1024)
    times = []
    compute_tile(spec, 1000, segment=segment)  # warmup/compile
    for _ in range(max(repeats * 3, 5)):  # per-tile turnaround distribution
        t0 = time.perf_counter()
        compute_tile(spec, 1000, segment=segment)
        times.append(time.perf_counter() - t0)
    times.sort()
    p50 = times[len(times) // 2]
    return {"metric": "config2 single-device 1024^2 mi=1000 seahorse",
            "value": round(_mpix(1024 * 1024, min(times)), 2),
            "unit": "Mpix/s", "p50_tile_turnaround_s": round(p50, 4)}


def bench_config3(repeats: int, segment: int) -> dict:
    """BASELINE config 3: 8x1024^2 batch, max_iter=5000, mesh-sharded,
    plus 1->N scaling efficiency."""
    jax, mesh, batched_escape_pixels = _mesh_and_kernel()
    params = _bench_params(1024, 8)
    mrds = np.full(8, 5000, dtype=np.int64)

    def run_mesh(m):
        return lambda: batched_escape_pixels(m, params, mrds, definition=1024,
                                             dtype=np.float32, segment=segment)

    t_n = _time_best(run_mesh(mesh), repeats)
    out = {"metric": f"config3 {mesh.devices.size}-device 8x1024^2 mi=5000",
           "value": round(_mpix(8 * 1024 * 1024, t_n), 2), "unit": "Mpix/s"}
    if mesh.devices.size > 1:
        from distributedmandelbrot_tpu.parallel import tile_mesh
        t_1 = _time_best(run_mesh(tile_mesh(1)), repeats)
        out["scaling_efficiency_1_to_n"] = round(
            t_1 / (t_n * mesh.devices.size), 3)
    return out


def bench_config4(repeats: int) -> dict:
    """BASELINE config 4: deep zoom at scale 1e-10, max_iter=50000,
    float64 + smooth coloring (128^2 probe tile)."""
    from distributedmandelbrot_tpu.core.geometry import TileSpec
    from distributedmandelbrot_tpu.ops import compute_tile_smooth

    # Misiurewicz-point neighborhood: boundary-rich at every depth.
    spec = TileSpec(-0.77568377, 0.13646737, 1e-10, 1e-10,
                    width=128, height=128)
    run = lambda: compute_tile_smooth(spec, 50000, dtype=np.float64)
    v = _mpix(128 * 128, _time_best(run, max(1, repeats - 1)))
    return {"metric": "config4 deep-zoom 1e-10 mi=50000 f64+smooth 128^2",
            "value": round(v, 3), "unit": "Mpix/s"}


def bench_config5(repeats: int, segment: int) -> dict:
    """BASELINE config 5 (local-mesh stand-in for v5e-16): 60-frame zoom,
    each frame a mesh-sharded tile batch through batched dispatch sizes.
    True multi-host needs a slice; this measures the per-host pipeline."""
    jax, mesh, batched_escape_pixels = _mesh_and_kernel()
    n = max(8, mesh.devices.size)
    frames = 60
    tile = 256  # keep the stand-in affordable; rate scales to 4096
    base_span = 3.0

    def run():
        for f in range(frames):
            span = base_span * (0.93 ** f)
            params = np.empty((n, 3))
            for i in range(n):
                params[i] = (SEAHORSE[0] - span / 2 + (i % 4) * span / 4,
                             SEAHORSE[1] - span / 2 + (i // 4) * span / 4,
                             span / 4 / (tile - 1))
            batched_escape_pixels(mesh, params, np.full(n, 1000, np.int64),
                                  definition=tile, dtype=np.float32,
                                  segment=segment)

    v = _mpix(frames * n * tile * tile, _time_best(run, max(1, repeats - 1)))
    return {"metric": f"config5 zoom-animation {frames}f x {n}x{tile}^2 "
                      f"mi=1000 ({mesh.devices.size} device(s))",
            "value": round(v, 2), "unit": "Mpix/s"}


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--tile", type=int, default=1024)
    parser.add_argument("--tiles", type=int, default=8)
    parser.add_argument("--max-iter", type=int, default=1000)
    parser.add_argument("--dtype", choices=["f32", "f64"], default="f32")
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--segment", type=int, default=256)
    parser.add_argument("--all", action="store_true",
                        help="run the 5 BASELINE.md configs (one JSON "
                             "line each) instead of the headline metric")
    args = parser.parse_args()

    if args.all:
        failed = 0
        for fn in (bench_config1,
                   lambda r: bench_config2(r, args.segment),
                   lambda r: bench_config3(r, args.segment),
                   bench_config4,
                   lambda r: bench_config5(r, args.segment)):
            try:
                print(json.dumps(fn(args.repeats)), flush=True)
            except Exception as e:  # finish the sweep, but fail the run
                failed += 1
                print(f"# config failed: {type(e).__name__}: {e}",
                      file=sys.stderr)
        return 1 if failed else 0

    result = bench_throughput(args.tile, args.tiles, args.max_iter,
                              args.dtype, args.repeats, args.segment)
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
