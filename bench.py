"""Benchmark harness: prints ONE JSON line with the headline metric.

Headline: escape-time throughput in Mpixels/s at max_iter=1000 on the
seahorse-valley zoom (BASELINE.md config 2 view), computed through the
production sharded path (device-side grids, batched tiles over the local
mesh).  ``vs_baseline`` is measured against the driver's north star of
500 Mpix/s (BASELINE.json) — set for a TPU v2-8; single-chip runs are
reported as-is.

Usage: python bench.py [--tile 1024] [--tiles N] [--max-iter 1000]
                       [--dtype f32] [--repeats 3] [--all]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

NORTH_STAR_MPIX_S = 500.0

# Seahorse valley: boundary-dense, iteration-heavy — a conservative view
# (full-view tiles with fast escapes bench much higher).
SEAHORSE = (-0.748, 0.09)


def _mesh_and_kernel():
    import jax

    from distributedmandelbrot_tpu.parallel import (batched_escape_pixels,
                                                    tile_mesh)
    mesh = tile_mesh()
    return jax, mesh, batched_escape_pixels


def bench_throughput(tile: int, tiles: int, max_iter: int, dtype: str,
                     repeats: int, segment: int = 256) -> dict:
    jax, mesh, batched_escape_pixels = _mesh_and_kernel()
    np_dtype = {"f32": np.float32, "f64": np.float64}[dtype]
    n_dev = mesh.devices.size
    # One batch = `tiles` sub-tiles of the seahorse window, tiled spatially.
    span = 0.005
    params = np.empty((tiles, 3))
    for i in range(tiles):
        params[i] = (SEAHORSE[0] + (i % 4) * span,
                     SEAHORSE[1] + (i // 4) * span,
                     span / (tile - 1))
    mrds = np.full(tiles, max_iter, dtype=np.int64)

    def run():
        return batched_escape_pixels(mesh, params, mrds, definition=tile,
                                     dtype=np_dtype, segment=segment)

    run()  # warmup/compile
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = run()
        times.append(time.perf_counter() - t0)
    best = min(times)
    pixels = tiles * tile * tile
    mpix_s = pixels / best / 1e6
    return {
        "metric": f"Mpixels/s @ max_iter={max_iter} "
                  f"({tiles}x{tile}^2 {dtype}, seahorse valley, "
                  f"{n_dev} {jax.devices()[0].platform} device(s))",
        "value": round(mpix_s, 2),
        "unit": "Mpix/s",
        "vs_baseline": round(mpix_s / NORTH_STAR_MPIX_S, 4),
    }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--tile", type=int, default=1024)
    parser.add_argument("--tiles", type=int, default=8)
    parser.add_argument("--max-iter", type=int, default=1000)
    parser.add_argument("--dtype", choices=["f32", "f64"], default="f32")
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--segment", type=int, default=256)
    args = parser.parse_args()

    result = bench_throughput(args.tile, args.tiles, args.max_iter,
                              args.dtype, args.repeats, args.segment)
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
