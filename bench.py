"""Benchmark harness: prints ONE JSON line with the headline metric.

Headline: escape-time throughput in Mpixels/s at max_iter=1000 on the
seahorse-valley zoom (BASELINE.md config 2 view), computed through the
production sharded path (device-side grids, batched tiles over the local
mesh).  ``vs_baseline`` is measured against the driver's north star of
500 Mpix/s (BASELINE.json) — set for a TPU v2-8; single-chip runs are
reported as-is.

Usage: python bench.py [--tile 1024] [--tiles N] [--max-iter 1000]
                       [--dtype f32] [--repeats 3] [--all]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

NORTH_STAR_MPIX_S = 500.0

# Seahorse valley: boundary-dense, iteration-heavy — a conservative view
# (full-view tiles with fast escapes bench much higher).
SEAHORSE = (-0.748, 0.09)


def _mesh_and_kernel():
    import jax

    from distributedmandelbrot_tpu.parallel import (batched_escape_pixels,
                                                    tile_mesh)
    mesh = tile_mesh()
    return jax, mesh, batched_escape_pixels


def _bench_params(tile: int, tiles: int):
    # One batch = `tiles` sub-tiles of the seahorse window, tiled spatially.
    span = 0.005
    params = np.empty((tiles, 3))
    for i in range(tiles):
        params[i] = (SEAHORSE[0] + (i % 4) * span,
                     SEAHORSE[1] + (i // 4) * span,
                     span / (tile - 1))
    return params


def _time_best(run, repeats: int) -> float:
    run()  # warmup/compile
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    return min(times)


def bench_throughput(tile: int, tiles: int, max_iter: int, dtype: str,
                     repeats: int, segment: int = 256) -> dict:
    """Fastest of the available compute paths (XLA sharded; Pallas on TPU)."""
    jax, mesh, batched_escape_pixels = _mesh_and_kernel()
    np_dtype = {"f32": np.float32, "f64": np.float64}[dtype]
    n_dev = mesh.devices.size
    params = _bench_params(tile, tiles)
    mrds = np.full(tiles, max_iter, dtype=np.int64)
    pixels = tiles * tile * tile

    results: dict[str, float] = {}

    def xla_run():
        return batched_escape_pixels(mesh, params, mrds, definition=tile,
                                     dtype=np_dtype, segment=segment)

    results["xla"] = pixels / _time_best(xla_run, repeats) / 1e6

    if dtype == "f32":
        try:  # Pallas path: block-granular early exit; TPU only.
            from distributedmandelbrot_tpu.core.geometry import TileSpec
            from distributedmandelbrot_tpu.ops.pallas_escape import (
                compute_tile_pallas, pallas_available)
            if pallas_available():
                specs = [TileSpec(p[0], p[1], p[2] * (tile - 1),
                                  p[2] * (tile - 1), tile, tile)
                         for p in params]

                def pallas_run():
                    for s in specs:
                        compute_tile_pallas(s, max_iter, segment=segment)

                results["pallas"] = \
                    pixels / _time_best(pallas_run, repeats) / 1e6
        except Exception as e:  # never let an experimental path kill bench
            print(f"# pallas path skipped: {type(e).__name__}: {e}",
                  file=sys.stderr)

    path, mpix_s = max(results.items(), key=lambda kv: kv[1])
    return {
        "metric": f"Mpixels/s @ max_iter={max_iter} "
                  f"({tiles}x{tile}^2 {dtype}, seahorse valley, "
                  f"{n_dev} {jax.devices()[0].platform} device(s), "
                  f"{path} path)",
        "value": round(mpix_s, 2),
        "unit": "Mpix/s",
        "vs_baseline": round(mpix_s / NORTH_STAR_MPIX_S, 4),
    }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--tile", type=int, default=1024)
    parser.add_argument("--tiles", type=int, default=8)
    parser.add_argument("--max-iter", type=int, default=1000)
    parser.add_argument("--dtype", choices=["f32", "f64"], default="f32")
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--segment", type=int, default=256)
    args = parser.parse_args()

    result = bench_throughput(args.tile, args.tiles, args.max_iter,
                              args.dtype, args.repeats, args.segment)
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
